"""Process-local metrics registry: counters, gauges, histograms.

One :class:`MetricsRegistry` per process (``default_registry()``) is the
single namespace every subsystem publishes into — ``RunStats`` deltas from
the :class:`~evox_tpu.resilience.ResilientRunner`, fleet supervisor
decisions, service admission/rejection accounting, ``EvalMonitor``
counters read off the checkpointed state at segment boundaries,
``CompileSentinel`` compile counts, and the async checkpoint writer's
publish/failure/block-seconds.  Two export shapes:

* :meth:`MetricsRegistry.snapshot` — a plain dict keyed by the
  label-qualified series name (``name{k="v"}``), for tests and in-process
  consumers;
* :meth:`MetricsRegistry.to_prometheus` /
  :meth:`MetricsRegistry.write_prometheus` — the Prometheus text
  exposition format, written atomically (temp + ``os.replace``) so a
  scraper's textfile collector never reads a torn snapshot.

Host metrics also ride the multi-host heartbeat plane for free:
:meth:`MetricsRegistry.heartbeat_payload` returns the flat
counters-and-gauges dict a :class:`~evox_tpu.parallel.HostHeartbeat`
merges into every beat (``HostHeartbeat(metrics=registry)``), so a
:class:`~evox_tpu.resilience.FleetSupervisor` reading the beats sees
per-host metrics without any extra transport.

Everything is thread-safe (one registry lock): the async checkpoint
writer publishes from its worker thread, heartbeat publishers from
theirs.  Kept stdlib-only (no jax import): ``bench.py``'s parent process
never initializes a JAX backend and loads this module by file path.
"""

from __future__ import annotations

import math
import os
import tempfile
import threading
from pathlib import Path
from typing import Any, Iterable, Mapping, Union

from .version import OBS_SCHEMA_VERSION

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "parse_series",
    "reset_default_registry",
]

# Prometheus' own default histogram buckets: a reasonable spread for the
# seconds-denominated timings (compile, execute, checkpoint block) the
# framework observes.
DEFAULT_BUCKETS = (
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
)


def _label_suffix(labels: Mapping[str, str]) -> str:
    """``{k="v",...}`` with keys sorted — one canonical series name per
    label set, whatever order call sites pass the labels in."""
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _unescape(value: str) -> str:
    out: list[str] = []
    i = 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append("\n" if nxt == "n" else nxt)
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def parse_series(series: str) -> tuple[str, dict[str, str]]:
    """Invert :func:`_label_suffix`: split a label-qualified series name
    (``name{k="v",...}``) back into ``(name, labels)``.  The ONE parser
    for the canonical series-string key shared by snapshots, heartbeat
    payloads, and the fleet aggregator — which needs the label set back
    to re-label per-host gauges with ``process_index``."""
    brace = series.find("{")
    if brace < 0:
        return series, {}
    name = series[:brace]
    inner = series[brace:]
    if not inner.endswith("}"):
        raise ValueError(f"malformed series {series!r}")
    labels: dict[str, str] = {}
    rest = inner[1:-1]
    pos = 0
    while pos < len(rest):
        eq = rest.find('="', pos)
        if eq < 0:
            raise ValueError(f"malformed series {series!r}")
        key = rest[pos:eq]
        # Find the closing quote, skipping escaped ones.
        scan = eq + 2
        while True:
            close = rest.find('"', scan)
            if close < 0:
                raise ValueError(f"malformed series {series!r}")
            backslashes = 0
            while rest[close - 1 - backslashes] == "\\":
                backslashes += 1
            if backslashes % 2 == 0:
                break
            scan = close + 1
        labels[key] = _unescape(rest[eq + 2 : close])
        pos = close + 1
        if pos < len(rest) and rest[pos] == ",":
            pos += 1
    return name, labels


class _Metric:
    """Shared handle plumbing: one instance per (name, label set)."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, labels: Mapping[str, str]):
        self._registry = registry
        self.name = name
        self.labels = dict(labels)

    @property
    def series(self) -> str:
        return self.name + _label_suffix(self.labels)


class Counter(_Metric):
    """Monotone counter.  ``inc`` with a negative amount is a ValueError —
    a counter that goes down is a gauge wearing the wrong type."""

    kind = "counter"

    def __init__(self, registry, name, labels):
        super().__init__(registry, name, labels)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease (inc {amount}); use a "
                f"gauge for values that go down"
            )
        with self._registry._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._registry._lock:
            return self._value

    def _sample(self) -> dict[str, float]:
        return {self.series: self._value}


class Gauge(_Metric):
    """Last-write-wins scalar."""

    kind = "gauge"

    def __init__(self, registry, name, labels):
        super().__init__(registry, name, labels)
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._registry._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._registry._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._registry._lock:
            return self._value

    def _sample(self) -> dict[str, float]:
        return {self.series: self._value}


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: each ``le``
    bucket counts observations at or below its bound, ``+Inf`` counts
    everything; ``_sum`` and ``_count`` ride alongside)."""

    kind = "histogram"

    def __init__(self, registry, name, labels, buckets: Iterable[float] = DEFAULT_BUCKETS):
        super().__init__(registry, name, labels)
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self.bounds = tuple(bounds)
        self._bucket_counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._registry._lock:
            self._sum += value
            self._count += 1
            for i, bound in enumerate(self.bounds):
                if value <= bound:
                    self._bucket_counts[i] += 1
            self._bucket_counts[-1] += 1

    def merge(
        self,
        bucket_deltas: Iterable[float],
        sum_delta: float,
        count_delta: float,
    ) -> None:
        """Fold another histogram's (delta) distribution into this one —
        the fleet aggregator's bucket-wise merge.  ``bucket_deltas`` must
        match this histogram's bucket count (bounds + ``+Inf``); negative
        deltas are a ValueError (a shrinking cumulative distribution is a
        counter reset, which the caller must detect and re-base first)."""
        deltas = [float(d) for d in bucket_deltas]
        if len(deltas) != len(self._bucket_counts):
            raise ValueError(
                f"histogram {self.name} merge expects "
                f"{len(self._bucket_counts)} bucket deltas, got {len(deltas)}"
            )
        if any(d < 0 for d in deltas) or count_delta < 0:
            raise ValueError(
                f"histogram {self.name} merge deltas cannot be negative"
            )
        with self._registry._lock:
            for i, d in enumerate(deltas):
                self._bucket_counts[i] += d
            self._sum += float(sum_delta)
            self._count += int(count_delta)

    @property
    def count(self) -> int:
        with self._registry._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._registry._lock:
            return self._sum

    def _sample(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for bound, count in zip(
            (*self.bounds, math.inf), self._bucket_counts
        ):
            le = "+Inf" if math.isinf(bound) else repr(bound)
            labels = dict(self.labels, le=le)
            out[f"{self.name}_bucket" + _label_suffix(labels)] = float(count)
        out[f"{self.name}_sum" + _label_suffix(self.labels)] = self._sum
        out[f"{self.name}_count" + _label_suffix(self.labels)] = float(
            self._count
        )
        return out


class MetricsRegistry:
    """A process-local family of named metrics with label sets.

    Handles are memoized: ``registry.counter("x", tenant_id="a")`` returns
    the same :class:`Counter` on every call, so call sites need no caching
    of their own.  Re-requesting a name as a different metric type is a
    loud ``ValueError`` — two subsystems silently sharing a name across
    types would corrupt the export.
    """

    def __init__(self) -> None:
        self._lock = threading.RLock()
        # name -> (kind, help); series handles live in _metrics.
        self._families: dict[str, tuple[str, str]] = {}
        self._metrics: dict[tuple[str, tuple], _Metric] = {}

    # -- handle construction ----------------------------------------------
    def _get(self, cls, name: str, help: str, labels: Mapping[str, str], **kw):
        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        with self._lock:
            family = self._families.get(name)
            if family is not None and family[0] != cls.kind:
                raise ValueError(
                    f"metric {name!r} is already registered as a "
                    f"{family[0]}, cannot re-register as a {cls.kind}"
                )
            if family is None or (help and not family[1]):
                self._families[name] = (cls.kind, help or (family[1] if family else ""))
            metric = self._metrics.get(key)
            if metric is None:
                metric = cls(
                    self,
                    name,
                    labels,
                    **{k: v for k, v in kw.items() if v is not None},
                )
                self._metrics[key] = metric
            elif isinstance(metric, Histogram) and kw.get("buckets") is not None:
                # Same loud-conflict contract as the type check: silently
                # returning the memoized handle with DIFFERENT buckets
                # would corrupt the distribution without a signal.  A
                # caller that omits buckets accepts whatever the series
                # was registered with — so framework call sites (which
                # never pass buckets) compose with user-customized ones.
                bounds = tuple(sorted(float(b) for b in kw["buckets"]))
                if bounds != metric.bounds:
                    raise ValueError(
                        f"histogram {name!r} is already registered with "
                        f"buckets {metric.bounds}, cannot re-register "
                        f"with {bounds}"
                    )
            return metric

    def counter(self, name: str, help: str = "", **labels: Any) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels: Any) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Iterable[float] | None = None,
        **labels: Any,
    ) -> Histogram:
        """``buckets=None`` (the default) means "whatever this series was
        (or will be) registered with" — ``DEFAULT_BUCKETS`` on first
        registration; an explicit bucket set that conflicts with an
        existing series raises."""
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def counter_sync(
        self, cursor: dict, name: str, value: float, help: str = ""
    ) -> None:
        """Publish a scope-local monotone stat (a ``RunStats`` field, a
        ``FleetStats`` field) as a process-level counter: increment by
        the delta against ``cursor`` (which the caller resets together
        with its stats object, so deltas stay non-negative across
        runs).  The one definition of the cursor-delta pattern the
        runner and the fleet supervisor share."""
        delta = value - cursor.get(name, 0.0)
        if delta > 0:
            self.counter(name, help).inc(delta)
        cursor[name] = value

    # -- exports ------------------------------------------------------------
    def snapshot(self) -> dict[str, float]:
        """Every series as ``{label-qualified name: value}`` — histograms
        expand into their ``_bucket``/``_sum``/``_count`` series."""
        with self._lock:
            out: dict[str, float] = {}
            for metric in self._metrics.values():
                out.update(metric._sample())
            return out

    def heartbeat_payload(self) -> dict[str, float]:
        """The flat counters-and-gauges dict that rides a
        :class:`~evox_tpu.parallel.HostHeartbeat` beat (histogram buckets
        are dropped: beats are small JSON files republished twice a
        second; ``_sum``/``_count`` still ride so rates are computable)."""
        with self._lock:
            out: dict[str, float] = {}
            for metric in self._metrics.values():
                if isinstance(metric, Histogram):
                    out[f"{metric.name}_sum" + _label_suffix(metric.labels)] = (
                        metric._sum
                    )
                    out[
                        f"{metric.name}_count" + _label_suffix(metric.labels)
                    ] = float(metric._count)
                else:
                    out.update(metric._sample())
            return out

    def fleet_payload(self) -> dict[str, Any]:
        """The typed snapshot that rides a
        :class:`~evox_tpu.parallel.HostHeartbeat` beat for fleet-level
        aggregation (:class:`~evox_tpu.obs.FleetAggregator`): counters
        and gauges as flat ``{series: value}`` sections, histograms with
        their full bucket arrays (``bounds``/``counts``/``sum``/``count``)
        — the flat :meth:`heartbeat_payload` cannot be merged bucket-wise.
        All JSON-serializable; ``schema`` stamps the obs schema version."""
        with self._lock:
            counters: dict[str, float] = {}
            gauges: dict[str, float] = {}
            histograms: dict[str, dict[str, Any]] = {}
            for metric in self._metrics.values():
                if isinstance(metric, Histogram):
                    histograms[metric.series] = {
                        "bounds": list(metric.bounds),
                        "counts": [float(c) for c in metric._bucket_counts],
                        "sum": metric._sum,
                        "count": float(metric._count),
                    }
                elif isinstance(metric, Counter):
                    counters[metric.series] = metric._value
                else:
                    gauges[metric.series] = metric._value
            return {
                "schema": OBS_SCHEMA_VERSION,
                "counters": counters,
                "gauges": gauges,
                "histograms": histograms,
            }

    def remove_series(self, name: str, **labels: Any) -> bool:
        """Drop exactly one series (by name + label set); returns whether
        it existed.  The fleet aggregator re-labels a stale host's gauges
        (``stale="true"``) by removing the fresh series and publishing the
        marked one — series identity is the label set, so the swap is a
        remove + re-register."""
        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        with self._lock:
            return self._metrics.pop(key, None) is not None

    def to_prometheus(self) -> str:
        """The Prometheus text exposition format (``# HELP``/``# TYPE``
        per family, one sample line per series), plus the obs schema
        version as its own gauge so a scrape is self-describing."""
        with self._lock:
            by_family: dict[str, list[str]] = {}
            # Series sorted by label set; within one series the sample
            # order is preserved (histogram buckets must stay in
            # ascending ``le`` order, which lexical sorting would break).
            for metric in sorted(
                self._metrics.values(), key=lambda m: m.series
            ):
                lines = by_family.setdefault(metric.name, [])
                for series, value in metric._sample().items():
                    lines.append(f"{series} {_format_value(value)}")
            out: list[str] = [
                "# HELP evox_obs_schema_version Observability schema version.",
                "# TYPE evox_obs_schema_version gauge",
                f"evox_obs_schema_version {OBS_SCHEMA_VERSION}",
            ]
            for name in sorted(by_family):
                kind, help = self._families.get(name, ("untyped", ""))
                if help:
                    out.append(f"# HELP {name} {help}")
                out.append(f"# TYPE {name} {kind}")
                out.extend(by_family[name])
            return "\n".join(out) + "\n"

    def write_prometheus(self, path: Union[str, Path]) -> Path:
        """Atomically publish :meth:`to_prometheus` to ``path`` (temp +
        ``os.replace``): a textfile-collector scrape racing the write sees
        the old snapshot or the new one, never a torn file."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        text = self.to_prometheus()
        fd, tmp = tempfile.mkstemp(
            dir=path.parent, prefix=path.name + ".tmp."
        )
        try:
            with os.fdopen(fd, "w") as f:
                f.write(text)
            os.replace(tmp, path)
            tmp = None
        finally:
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
        return path

    def remove_labeled(self, label: str, value: Any) -> int:
        """Drop every series carrying ``label == value``; returns how many
        were removed.  High-churn label values (the service's
        ``tenant_id``) would otherwise accumulate immortal series — and
        grow every Prometheus snapshot and heartbeat payload — long after
        their subject is gone; the service calls this from ``forget()``."""
        value = str(value)
        with self._lock:
            doomed = [
                key
                for key, metric in self._metrics.items()
                if str(metric.labels.get(label)) == value
                and label in metric.labels
            ]
            for key in doomed:
                del self._metrics[key]
            return len(doomed)

    def clear(self) -> None:
        """Drop every registered series (tests; a fresh run in a live
        process should usually use a fresh registry instead)."""
        with self._lock:
            self._families.clear()
            self._metrics.clear()


def _format_value(value: float) -> str:
    # Non-finite first (int() would raise), in the spellings the
    # Prometheus text format actually parses.
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(value)


_DEFAULT_LOCK = threading.Lock()
_DEFAULT: MetricsRegistry | None = None


def default_registry() -> MetricsRegistry:
    """The process-local registry every subsystem publishes into unless
    handed an explicit one."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsRegistry()
        return _DEFAULT


def reset_default_registry() -> MetricsRegistry:
    """Swap in a fresh process-local registry (tests) and return it."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = MetricsRegistry()
        return _DEFAULT
