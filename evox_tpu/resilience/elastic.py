"""Elastic-topology resilience: re-meshable checkpoints for distributed runs.

The reference's distributed mode is a fixed-world ``torchrun`` + NCCL
all-gather: the world size is baked in at launch, and a rank dying — or the
job being rescheduled onto a different slice shape — loses the run.  PR 1's
:class:`~evox_tpu.resilience.ResilientRunner` hardened *single-topology*
runs; this module makes the topology itself elastic:

* :class:`MeshTopology` — a serializable record of the device world a
  checkpoint was written under (mesh axis names/sizes, device kind,
  platform, global device count, process count).  Every checkpoint manifest
  written by the runner (and, in its environment-level form, by
  :func:`~evox_tpu.utils.save_state` itself) carries one, so resume logic
  can see a topology change *before* deserializing gigabytes of state.
* :func:`check_topology` — the compatibility gate: a recorded topology that
  differs from the current one raises a structured
  :class:`~evox_tpu.utils.CheckpointError` (naming both worlds and the fix)
  when re-meshing is disabled, and validates divisibility when it is
  enabled.
* :func:`remesh_state` — repartitions a restored state pytree for a new
  mesh: leaves with a population-sized leading axis are sharded over the
  population axis, everything else is replicated (the replicated-state
  contract of the parallel layer).

**Why resume across topologies is bit-identical.**  All checkpointed state
is *global* (full populations, replicated algorithm state — the gather
happens before any checkpoint), and per-individual PRNG decorrelation in
:class:`~evox_tpu.parallel.ShardedProblem` folds the **global slot index**
rather than the shard index, so no value in the trajectory depends on which
device computed it.  A run checkpointed on an 8-device ``pop`` mesh
therefore resumes on 4 (or 2, or 1) devices with exactly the trajectory the
uninterrupted 8-device run would have produced
(``tests/test_elastic.py::test_elastic_resume_bit_identical``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.checkpoint import CheckpointError

__all__ = [
    "MeshTopology",
    "current_topology",
    "workflow_topology",
    "workflow_mesh",
    "check_topology",
    "topology_differs",
    "remesh_state",
]

TOPOLOGY_KEY = "topology"


@dataclass(frozen=True)
class MeshTopology:
    """The device world a run executes (or was checkpointed) under.

    ``axis_names``/``axis_sizes`` are empty for meshless (single-program)
    runs — the environment fields still record where the checkpoint was
    written, which :func:`check_topology` treats as informational rather
    than binding (a single-device state loads anywhere)."""

    axis_names: tuple[str, ...]
    axis_sizes: tuple[int, ...]
    device_kind: str
    platform: str
    num_devices: int
    num_processes: int

    # -- constructors --------------------------------------------------------
    @classmethod
    def from_mesh(cls, mesh: Mesh) -> "MeshTopology":
        dev = mesh.devices.flat[0]
        return cls(
            axis_names=tuple(str(n) for n in mesh.axis_names),
            axis_sizes=tuple(int(mesh.shape[n]) for n in mesh.axis_names),
            device_kind=str(getattr(dev, "device_kind", "unknown")),
            platform=str(getattr(dev, "platform", "unknown")),
            num_devices=int(mesh.devices.size),
            num_processes=int(jax.process_count()),
        )

    @classmethod
    def from_manifest(cls, entry: Mapping[str, Any]) -> "MeshTopology":
        return cls(
            axis_names=tuple(entry.get("axis_names", ())),
            axis_sizes=tuple(int(s) for s in entry.get("axis_sizes", ())),
            device_kind=str(entry.get("device_kind", "unknown")),
            platform=str(entry.get("platform", "unknown")),
            num_devices=int(entry.get("num_devices", 0)),
            num_processes=int(entry.get("num_processes", 1)),
        )

    # -- queries -------------------------------------------------------------
    @property
    def meshed(self) -> bool:
        """Whether this world binds state to a mesh (vs a plain device)."""
        return bool(self.axis_names)

    @property
    def mesh_size(self) -> int:
        """Total shard count over all mesh axes (1 for meshless worlds)."""
        n = 1
        for s in self.axis_sizes:
            n *= s
        return n

    def describe(self) -> str:
        if self.meshed:
            axes = ", ".join(
                f"{n}={s}" for n, s in zip(self.axis_names, self.axis_sizes)
            )
            return (
                f"{self.num_devices}-device {self.platform} mesh ({axes}; "
                f"{self.num_processes} process(es))"
            )
        return (
            f"meshless {self.platform} world ({self.num_devices} device(s), "
            f"{self.num_processes} process(es))"
        )

    # -- manifest round-trip -------------------------------------------------
    def to_manifest(self) -> dict[str, Any]:
        return {
            "axis_names": list(self.axis_names),
            "axis_sizes": list(self.axis_sizes),
            "device_kind": self.device_kind,
            "platform": self.platform,
            "num_devices": self.num_devices,
            "num_processes": self.num_processes,
        }


def current_topology() -> MeshTopology:
    """The meshless environment-level topology of this process — what
    :func:`~evox_tpu.utils.save_state` stamps on every checkpoint so even
    non-runner checkpoints record where they were written."""
    dev = jax.devices()[0]
    return MeshTopology(
        axis_names=(),
        axis_sizes=(),
        device_kind=str(getattr(dev, "device_kind", "unknown")),
        platform=str(getattr(dev, "platform", "unknown")),
        num_devices=int(jax.device_count()),
        num_processes=int(jax.process_count()),
    )


def workflow_mesh(workflow: Any) -> tuple[Mesh, str] | None:
    """The ``(mesh, population_axis)`` a workflow evaluates over, if any:
    ``StdWorkflow``'s own ``mesh``/``pop_axis``, else the mesh of a
    ``ShardedProblem`` it composes (unwrapping fault-injection / transform
    layers via the shared :func:`~evox_tpu.parallel.iter_problem_chain`
    walk)."""
    mesh = getattr(workflow, "mesh", None)
    if isinstance(mesh, Mesh):
        axis = getattr(workflow, "pop_axis", None) or mesh.axis_names[0]
        return mesh, str(axis)
    from ..parallel import find_sharded

    sharded = find_sharded(getattr(workflow, "problem", None))
    if sharded is not None:
        return sharded.mesh, str(sharded.axis_name)
    return None


def workflow_topology(workflow: Any) -> MeshTopology:
    """The topology a workflow's run binds to: its mesh when it evaluates
    distributed (directly or through any wrapper holding a
    ``ShardedProblem``), else the meshless environment topology."""
    meshed = workflow_mesh(workflow)
    if meshed is not None:
        return MeshTopology.from_mesh(meshed[0])
    return current_topology()


def topology_differs(
    recorded: MeshTopology | None, current: MeshTopology | None
) -> bool:
    """The ONE mesh-compatibility predicate: do these two worlds bind state
    to different meshes?  Meshless on either side is never a difference
    (checkpointed state is global — see :func:`check_topology`)."""
    return (
        recorded is not None
        and current is not None
        and recorded.meshed
        and current.meshed
        and (
            recorded.axis_names != current.axis_names
            or recorded.axis_sizes != current.axis_sizes
        )
    )


def check_topology(
    recorded: Mapping[str, Any] | MeshTopology | None,
    current: MeshTopology | None,
    *,
    remesh: bool = True,
    pop_size: int | None = None,
    pop_axis: str | None = None,
    context: str = "checkpoint",
) -> MeshTopology | None:
    """Gate a resume across a topology change.

    :param recorded: the checkpoint manifest's ``topology`` entry (dict or
        :class:`MeshTopology`); ``None`` for pre-topology checkpoints (no
        gate — they load as before).
    :param current: the topology the resuming run will execute under.
    :param remesh: whether cross-topology resume is allowed.  ``False``
        turns any mesh mismatch into a structured
        :class:`~evox_tpu.utils.CheckpointError` naming both worlds —
        instead of the shape blowup (or silent trajectory fork) a blind
        load would produce.
    :param pop_size: when known, the population size that must divide the
        current mesh's population axis — a re-mesh onto a mesh the
        population cannot shard over fails here, with the fix in the
        message, not deep inside ``shard_map``.
    :param pop_axis: name of the population axis of the current mesh (for
        multi-axis meshes, where only that axis's size governs
        divisibility); defaults to the first axis.
    :param context: noun used in error messages (checkpoint path etc.).
    :returns: the recorded topology (parsed), or ``None`` when the manifest
        predates topology recording.
    :raises CheckpointError: incompatible topology per the rules above.
    """
    if recorded is None:
        return None
    if not isinstance(recorded, MeshTopology):
        recorded = MeshTopology.from_manifest(recorded)
    # A meshless world on either side is benign: checkpointed state is
    # always global (populations gathered before the write), so it is only
    # *bound* to a topology when both the writer and the reader mesh it —
    # device-count changes alone never invalidate a load.
    mismatch = topology_differs(recorded, current)
    if mismatch and not remesh:
        raise CheckpointError(
            f"{context} was written on a {recorded.describe()} but this run "
            f"executes on a {current.describe()}, and re-meshing is "
            f"disabled — resume on the original topology, or enable "
            f"re-meshing (ResilientRunner(remesh=True) / "
            f"load_state(..., remesh=True)) to repartition the state"
        )
    if mismatch and pop_size is not None:
        # Only the POPULATION axis governs divisibility (a multi-axis mesh
        # may shard models/data on its other axes).
        if pop_axis is not None and pop_axis in current.axis_names:
            n_shards = current.axis_sizes[
                current.axis_names.index(pop_axis)
            ]
        else:
            n_shards = current.axis_sizes[0]
        if pop_size % n_shards != 0:
            raise CheckpointError(
                f"{context} re-mesh from a {recorded.describe()} onto a "
                f"{current.describe()} is impossible for population size "
                f"{pop_size}: it does not divide the {n_shards}-way "
                f"population axis — resume on a mesh whose population axis "
                f"divides {pop_size}, or enable population padding "
                f"(ShardedProblem(pad=True))"
            )
    return recorded


def remesh_state(
    state: Any,
    mesh: Mesh,
    axis_name: str | None = None,
    pop_size: int | None = None,
) -> Any:
    """Repartition a (restored) state pytree for ``mesh``: leaves whose
    leading axis equals ``pop_size`` are sharded over ``axis_name``,
    everything else is replicated — the parallel layer's placement contract
    (``parallel/mesh.py``), applied wholesale to a checkpoint that was
    written under a different topology.

    ``axis_name`` defaults to the mesh's first axis (whatever it is named),
    and ``pop_size`` to the leading dimension of ``state.algorithm.pop``
    when the state carries one; with no discoverable population the whole
    tree is replicated (correct, if not bandwidth-optimal — XLA re-shards
    at the next ``shard_map`` entry).

    **Multi-process meshes** (a ``jax.distributed`` fleet re-meshing after
    a host-count change) skip explicit placement entirely: ``device_put``
    onto a sharding that spans other processes' devices is refused, and
    the restored leaves are global host values anyway — the next jitted
    dispatch places them under the new mesh.  Same values, placement one
    dispatch later."""
    if any(
        getattr(d, "process_index", 0) != jax.process_index()
        for d in mesh.devices.flat
    ):
        return state
    if axis_name is None:
        axis_name = str(mesh.axis_names[0])
    if pop_size is None:
        algo = state.get("algorithm") if hasattr(state, "get") else None
        pop = algo.get("pop") if hasattr(algo, "get") else None
        pop_size = getattr(pop, "shape", (None,))[0] if pop is not None else None
    # device_put refuses uneven shardings, so a population that does not
    # divide the axis (legal under ShardedProblem(pad=True), which pads
    # inside the step) is replicated instead — correct placement, just one
    # resharding away from optimal.
    if pop_size is not None and pop_size % mesh.shape[axis_name] != 0:
        pop_size = None
    sharded = NamedSharding(mesh, P(axis_name))
    replicated = NamedSharding(mesh, P())

    def place(leaf):
        if (
            pop_size is not None
            and getattr(leaf, "ndim", 0) >= 1
            and leaf.shape[0] == pop_size
        ):
            return jax.device_put(leaf, sharded)
        return jax.device_put(leaf, replicated)

    return jax.tree_util.tree_map(place, state)
