"""Deterministic network chaos for the gateway wire: FaultyTransport.

:class:`FaultyTransport` is the wire-side twin of
:class:`~evox_tpu.resilience.FaultyStore`: it wraps any client transport
(an object with ``request(method, path, headers, body) -> (status,
headers, body_bytes)`` — :class:`~evox_tpu.service.client.HttpTransport`
in practice) and injects faults by **request index** (0-based count of
requests routed through this instance), the same scheduling idiom
FaultyProblem uses for eval faults and FaultyStore for save faults.

The faults model the four ways a network loses a request/reply pair, and
they matter differently on each side of the journal append:

* ``drop_requests`` — the request is never delivered: the wrapped
  transport is **not** called, :class:`TransportError` is raised.  No
  server-side effect; a retry is trivially safe.
* ``drop_replies`` — the request **is** delivered (the wrapped transport
  runs to completion, so the server appended its journal record and sent
  an ack) but the reply is discarded and :class:`TransportError` is
  raised.  This is the post-append/pre-reply crash window seen from the
  client: the only thing that makes the client's retry safe is the
  idempotency key riding the journal.
* ``torn_replies`` — the reply body is truncated to ``torn_fraction`` of
  its bytes (a connection reset mid-body).  The client sees a parse
  failure and must treat it exactly like a dropped reply.
* ``duplicate_requests`` — the request is delivered **twice** (retransmit
  of a packet the server already processed); the second reply is
  returned.  The server must dedup — one admission, two acks.
* ``delay_requests`` — the request sleeps ``delay_seconds`` before
  delivery (congestion; exercises client timeouts and long-poll overlap).

Request indices count *attempts through this wrapper*: a dropped request
still consumes its index, so "the retry succeeds" schedules naturally.
``events`` records one ``(index, kind)`` tuple per fired fault and
``requests`` counts attempts, for test assertions.

Stdlib-only; no jax import (the client side of the wire must stay cheap
to spawn in a separate process).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Sequence

from .schedule import validate_schedule

__all__ = ["FaultyTransport", "TransportError"]


class TransportError(ConnectionError):
    """A request or reply was lost on the wire (injected or real).

    Subclasses :class:`ConnectionError` so client retry loops that catch
    ``OSError`` — the base of every real socket failure — handle injected
    chaos through the same path as the real thing.
    """


class FaultyTransport:
    """Wrap a transport and lose/duplicate/tear/delay scheduled requests.

    :param inner: the real transport; anything with
        ``request(method, path, headers, body)``.
    :param drop_requests: request indices never delivered (inner not
        called; :class:`TransportError`).
    :param drop_replies: request indices delivered but whose reply is
        discarded (:class:`TransportError` *after* the inner call — the
        server-side effect happened).
    :param torn_replies: request indices whose reply body is truncated
        to ``torn_fraction`` of its bytes.
    :param duplicate_requests: request indices delivered twice
        back-to-back; the second reply wins.
    :param delay_requests: request indices delayed ``delay_seconds``
        before delivery.
    """

    def __init__(
        self,
        inner: Any,
        *,
        drop_requests: Sequence[int] = (),
        drop_replies: Sequence[int] = (),
        torn_replies: Sequence[int] = (),
        torn_fraction: float = 0.5,
        duplicate_requests: Sequence[int] = (),
        delay_requests: Sequence[int] = (),
        delay_seconds: float = 0.05,
    ):
        # Construction-time audit, the FaultyProblem discipline: negative
        # request indices and one request scheduled for two incompatible
        # fates (a never-delivered request has no reply to drop, tear, or
        # duplicate; a dropped reply is never observed torn) fail loudly
        # here, never lazily mid-run.
        schedules = validate_schedule(
            "FaultyTransport",
            indices={
                "drop_requests": drop_requests,
                "drop_replies": drop_replies,
                "torn_replies": torn_replies,
                "duplicate_requests": duplicate_requests,
                "delay_requests": delay_requests,
            },
            nonneg={
                "torn_fraction": float(torn_fraction),
                "delay_seconds": float(delay_seconds),
            },
            exclusive=[
                ("drop_requests", "drop_replies"),
                ("drop_requests", "torn_replies"),
                ("drop_requests", "duplicate_requests"),
                ("drop_replies", "torn_replies"),
            ],
        )
        self.inner = inner
        self.drop_requests = schedules["drop_requests"]
        self.drop_replies = schedules["drop_replies"]
        self.torn_replies = schedules["torn_replies"]
        self.torn_fraction = float(torn_fraction)
        self.duplicate_requests = schedules["duplicate_requests"]
        self.delay_requests = schedules["delay_requests"]
        self.delay_seconds = float(delay_seconds)
        self._lock = threading.Lock()
        self.requests = 0  # attempts routed through this wrapper
        self.events: list[tuple[int, str]] = []

    def request(
        self,
        method: str,
        path: str,
        headers: dict[str, str],
        body: bytes,
    ) -> tuple[int, dict[str, str], bytes]:
        with self._lock:
            index = self.requests
            self.requests += 1
        if index in self.delay_requests:
            with self._lock:
                self.events.append((index, "delay"))
            time.sleep(self.delay_seconds)
        if index in self.drop_requests:
            with self._lock:
                self.events.append((index, "drop-request"))
            raise TransportError(
                f"injected: request #{index} {method} {path} never delivered"
            )
        status, reply_headers, reply_body = self.inner.request(
            method, path, headers, body
        )
        if index in self.duplicate_requests:
            with self._lock:
                self.events.append((index, "duplicate-request"))
            status, reply_headers, reply_body = self.inner.request(
                method, path, headers, body
            )
        if index in self.drop_replies:
            with self._lock:
                self.events.append((index, "drop-reply"))
            raise TransportError(
                f"injected: reply to #{index} {method} {path} lost "
                f"(server already processed the request)"
            )
        if index in self.torn_replies:
            with self._lock:
                self.events.append((index, "torn-reply"))
            keep = max(1, int(len(reply_body) * self.torn_fraction))
            reply_body = reply_body[:keep]
        return status, reply_headers, reply_body
