"""Public chaos-test scaffolding: the ONE definition of the
kill-at-every-boundary matrices and the bit-identity comparators.

Four suites grew the same machinery independently — ``test_daemon``'s
kill-restart matrix, ``test_gateway``'s HTTP matrix, ``test_router``'s
forward-boundary matrix, and ``test_preemption``'s state/digest
comparators.  This module extracts them once, public, so downstream users
hardening their own deployments (and the chaos conductor's own suite)
drive the exact same boundaries and comparisons the repo's acceptance
tests do:

* :func:`kill_points` — the canonical SIGKILL boundaries per serving
  plane.  SIGKILL is always modelled as **abandonment**: the object is
  dropped with no shutdown path running (exactly what SIGKILL guarantees
  — no handler, no flush, no destructor) and a fresh instance is rebuilt
  over the same root.
* :func:`assert_states_equal` / :func:`npify` — PRNG-aware bit-identity
  over state pytrees (``jax.random`` key arrays compare by key data).
* :func:`last_checkpoint_digests` / :func:`verify_tenants_bit_identical`
  — the checkpoint-digest compare and the shared tail of every kill
  matrix: each tenant COMPLETED with final state and newest-checkpoint
  leaf digests bit-identical to an uninterrupted reference run.
* :func:`flip_bit` — single-bit on-disk corruption (the signature SHA-256
  leaf digests exist for).
* :func:`silent` / :func:`run_silently` — run a callable/daemon with
  warnings muted (chaos runs *warn loudly* by design; the tests assert
  the recovery outcome, not the noise).

Imported explicitly (``from evox_tpu.resilience.testing import ...``):
it needs jax and the checkpoint manifest reader, which the lean
``evox_tpu.resilience`` namespace must not drag in for the wire-client
case.
"""

from __future__ import annotations

import os
import warnings
from pathlib import Path
from typing import Any, Mapping, Union

import jax
import numpy as np

from ..utils.checkpoint import read_manifest

__all__ = [
    "KILL_POINTS",
    "kill_points",
    "npify",
    "assert_states_equal",
    "last_checkpoint_digests",
    "verify_tenants_bit_identical",
    "flip_bit",
    "silent",
    "run_silently",
]

#: The canonical kill-at-every-boundary matrices, one entry per serving
#: plane.  Each name is a lifecycle point a SIGKILL lands at; every plane's
#: acceptance test parametrizes over its tuple, and the chaos plan DSL
#: schedules process kills at the same boundaries.
KILL_POINTS: dict[str, tuple[str, ...]] = {
    # ServiceDaemon lifecycle (test_daemon's kill-restart matrix).
    "daemon": (
        "post-submit-pre-journal-ack",
        "post-ack-pre-admit",
        "mid-run",
        "post-checkpoint",
    ),
    # Gateway HTTP lifecycle (test_gateway's HTTP matrix): the same
    # daemon boundaries as seen from the wire, where the pre/post journal
    # split becomes pre-append vs post-append/pre-reply.
    "gateway": (
        "pre-append",
        "post-append-pre-reply",
        "mid-run",
        "post-checkpoint",
    ),
    # TenantRouter submit path (test_router's forward-boundary matrix).
    "router": (
        "pre-journal",
        "post-journal-pre-forward",
        "post-forward-pre-ack",
    ),
}


def kill_points(plane: str) -> tuple[str, ...]:
    """The canonical SIGKILL boundaries for one serving plane
    (``"daemon"`` / ``"gateway"`` / ``"router"``)."""
    try:
        return KILL_POINTS[plane]
    except KeyError:
        raise ValueError(
            f"unknown plane {plane!r}; kill matrices exist for "
            f"{sorted(KILL_POINTS)}"
        ) from None


def npify(x: Any) -> np.ndarray:
    """One leaf to a comparable numpy array; typed PRNG keys compare by
    their key data (``jax.random.key_data``), everything else directly."""
    if isinstance(x, jax.Array) and jax.dtypes.issubdtype(
        x.dtype, jax.dtypes.prng_key
    ):
        return np.asarray(jax.random.key_data(x))
    return np.asarray(x)


def assert_states_equal(a: Any, b: Any, context: str = "") -> None:
    """Bit-identity over two state pytrees, leaf by leaf (PRNG-aware);
    an ``AssertionError`` names the first differing leaf path."""
    leaves_a = jax.tree_util.tree_leaves_with_path(a)
    leaves_b = jax.tree_util.tree_leaves(b)
    # Explicit raises (not bare asserts): these verdicts must survive
    # ``python -O`` — a stripped bit-identity check is no check at all.
    if len(leaves_a) != len(leaves_b):
        raise AssertionError(
            f"{context}: leaf count differs "
            f"({len(leaves_a)} != {len(leaves_b)})"
        )
    for (path, la), lb in zip(leaves_a, leaves_b):
        if not np.array_equal(npify(la), npify(lb)):
            raise AssertionError(
                f"{context}: leaf {jax.tree_util.keystr(path)} differs"
            )


def last_checkpoint_digests(
    root: Union[str, Path], tenant_id: str
) -> tuple[str, dict[str, str]]:
    """(newest checkpoint filename, its manifest's per-leaf SHA-256
    digests) for one tenant namespace — the durable half of the
    bit-identity compare."""
    ns = os.path.join(str(root), "tenants", tenant_id)
    newest = sorted(f for f in os.listdir(ns) if f.endswith(".npz"))[-1]
    manifest = read_manifest(os.path.join(ns, newest))
    return newest, manifest["leaf_digests"]


def verify_tenants_bit_identical(
    daemon: Any,
    root: Union[str, Path],
    expected: Mapping[str, Any],
    expected_digests: Mapping[str, tuple[str, dict[str, str]]],
    context: str = "",
) -> None:
    """The shared tail of every kill matrix: each expected tenant is
    COMPLETED on ``daemon`` with result state and newest-checkpoint leaf
    digests bit-identical to the uninterrupted reference run."""
    from ..service import TenantStatus

    for tenant_id in expected:
        record = daemon.tenant(tenant_id)
        if record.status is not TenantStatus.COMPLETED:
            raise AssertionError(
                f"{context}: {tenant_id} is {record.status}, not COMPLETED"
            )
        assert_states_equal(
            expected[tenant_id],
            daemon.result(tenant_id),
            f"{context}: {tenant_id}",
        )
        name, digests = last_checkpoint_digests(root, tenant_id)
        if (name, digests) != expected_digests[tenant_id]:
            raise AssertionError(
                f"{context}: {tenant_id} final checkpoint digests differ"
            )


def flip_bit(path: Union[str, Path], offset: int | None = None) -> None:
    """Flip one bit of a file in place (mid-file by default): bit rot
    that ``np.load`` reads back without complaint — the case per-leaf
    SHA-256 digests exist for."""
    path = Path(path)
    raw = bytearray(path.read_bytes())
    raw[(len(raw) // 2) if offset is None else offset] ^= 0x01
    # Deliberately non-atomic, in place: this helper EXISTS to model the
    # torn/bit-rotted publish the store seam defends against.
    path.write_bytes(bytes(raw))  # graftlint: disable=GL009


def silent(fn: Any, *args: Any, **kwargs: Any) -> Any:
    """Call ``fn`` with all warnings muted; returns its result."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return fn(*args, **kwargs)


def run_silently(steppable: Any, *args: Any, **kwargs: Any) -> None:
    """``steppable.run(...)`` with all warnings muted (daemons and
    routers warn loudly through injected chaos, by design)."""
    silent(steppable.run, *args, **kwargs)
