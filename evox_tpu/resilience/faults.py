"""Deterministic fault injection for testing recovery paths on CPU.

:class:`FaultyProblem` wraps any :class:`~evox_tpu.core.Problem` and injects
faults by **evaluation schedule** (0-based evaluation index, counted in the
wrapper's own jitted state, so the schedule survives checkpoint/resume and
replays deterministically):

* **NaN rows** — the first ``nan_rows`` fitness entries of scheduled
  evaluations become NaN *inside the jitted program*, exercising the
  workflow's non-finite quarantine without leaving XLA.
* **host-side exceptions** — an ``io_callback`` raises
  :class:`InjectedBackendError` (message carries ``UNAVAILABLE``, the
  BASELINE.md outage signature); XLA wraps it into the same
  ``XlaRuntimeError: INTERNAL: CpuCallback error`` a real backend loss
  produces, so the runner's retry predicate sees exactly what production
  would show it.  :class:`InjectedFatalError` carries the ``NONRETRYABLE``
  marker instead — it simulates a genuine crash/process kill that retry must
  NOT paper over.
* **artificial delays** — the host callback sleeps, driving the runner's
  watchdog path (the silent-hang signature).

Transient faults are **attempt-counted on the host side**: a fault fires for
its first ``*_times`` attempts of a given evaluation index and then stops,
modeling an outage that passes — which is what lets retry/resume tests
complete.  Counters live on the wrapper instance (host memory), not in the
jitted state: a retry that reloads the checkpoint rolls the evaluation index
back but must still see the outage as "over".
"""

from __future__ import annotations

import threading
import time
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.experimental import io_callback
from jax.sharding import SingleDeviceSharding

from ..core import Problem, State

__all__ = ["FaultyProblem", "InjectedBackendError", "InjectedFatalError"]


class InjectedBackendError(RuntimeError):
    """Simulated transient backend loss (retryable signature)."""


class InjectedFatalError(RuntimeError):
    """Simulated unrecoverable crash (carries the NONRETRYABLE marker)."""


class FaultyProblem(Problem):
    """Wraps a problem with a deterministic, generation-scheduled fault plan.

    The wrapper is numerically transparent (same fitness, no extra PRNG use)
    — host faults raise/sleep but never touch the data path, and NaN
    injection only fires on scheduled evaluations.  For bit-identical
    clean-run comparators, keep the *program structure* identical too: build
    the comparator with the SAME schedule but ``*_times=0`` (the host
    callback stays in the compiled program — XLA fusion, and therefore
    ulp-level float results, can differ between programs with and without
    the callback op).
    """

    def __init__(
        self,
        problem: Problem,
        *,
        nan_generations: Sequence[int] = (),
        nan_rows: int = 1,
        error_generations: Sequence[int] = (),
        error_times: int = 1,
        error_message: str = "UNAVAILABLE: injected backend loss (fault schedule)",
        fatal_generations: Sequence[int] = (),
        fatal_times: int = 1,
        delay_generations: Sequence[int] = (),
        delay_seconds: float = 1.0,
        delay_times: int = 1,
    ):
        """
        :param nan_generations: evaluation indices whose fitness gets NaN
            injected into its first ``nan_rows`` rows (inside jit).
        :param error_generations: evaluation indices that raise a retryable
            :class:`InjectedBackendError` from the host, for the first
            ``error_times`` attempts each.
        :param fatal_generations: evaluation indices that raise a
            ``NONRETRYABLE`` :class:`InjectedFatalError` for the first
            ``fatal_times`` attempts each (simulated kill; a supervisor
            must surface it, and a later resume gets past it).
        :param delay_generations: evaluation indices whose host callback
            sleeps ``delay_seconds`` for the first ``delay_times`` attempts
            each (watchdog fodder).
        """
        self.problem = problem
        self.nan_generations = tuple(int(g) for g in nan_generations)
        self.nan_rows = int(nan_rows)
        self.error_generations = frozenset(int(g) for g in error_generations)
        self.error_times = int(error_times)
        self.error_message = error_message
        self.fatal_generations = frozenset(int(g) for g in fatal_generations)
        self.fatal_times = int(fatal_times)
        self.delay_generations = frozenset(int(g) for g in delay_generations)
        self.delay_seconds = float(delay_seconds)
        self.delay_times = int(delay_times)
        self._lock = threading.Lock()
        self._attempts: dict[tuple[str, int], int] = {}
        self._has_host_faults = bool(
            self.error_generations
            or self.fatal_generations
            or self.delay_generations
        )

    # -- host side ---------------------------------------------------------
    def _bump(self, kind: str, gen: int) -> int:
        with self._lock:
            n = self._attempts.get((kind, gen), 0) + 1
            self._attempts[(kind, gen)] = n
            return n

    def attempts(self, kind: str, gen: int) -> int:
        """How many times the ``kind`` fault at evaluation ``gen`` has been
        reached so far (test observability)."""
        with self._lock:
            return self._attempts.get((kind, gen), 0)

    def reset_faults(self) -> None:
        """Forget all attempt counts (faults re-arm)."""
        with self._lock:
            self._attempts.clear()

    def _host_hook(self, gen) -> None:
        g = int(gen)
        if g in self.fatal_generations:
            if self._bump("fatal", g) <= self.fatal_times:
                raise InjectedFatalError(
                    f"NONRETRYABLE: injected unrecoverable crash at "
                    f"evaluation {g} (simulated process kill)"
                )
        if g in self.error_generations:
            if self._bump("error", g) <= self.error_times:
                raise InjectedBackendError(f"{self.error_message} [eval {g}]")
        if g in self.delay_generations:
            if self._bump("delay", g) <= self.delay_times:
                time.sleep(self.delay_seconds)

    # -- component protocol ------------------------------------------------
    def setup(self, key: jax.Array) -> State:
        return State(
            inner=self.problem.setup(key),
            # 0-based evaluation index; lives in the jitted state so it is
            # checkpointed and rolls back with the run on resume.
            fault_generation=jnp.int32(0),
        )

    def evaluate(self, state: State, pop: jax.Array) -> tuple[jax.Array, State]:
        gen = state.fault_generation
        if self._has_host_faults:
            # Ordered + pinned to one device: fires exactly once per
            # evaluation, in program order, like a real backend fault would.
            io_callback(
                self._host_hook,
                None,
                gen,
                ordered=True,
                sharding=SingleDeviceSharding(jax.local_devices()[0]),
            )
        fit, inner = self.problem.evaluate(state.inner, pop)
        if self.nan_generations:
            scheduled = jnp.any(
                gen == jnp.asarray(self.nan_generations, jnp.int32)
            )
            rows = jnp.arange(fit.shape[0]) < self.nan_rows
            mask = rows if fit.ndim == 1 else rows[:, None]
            fit = jnp.where(
                jnp.logical_and(scheduled, mask),
                jnp.asarray(jnp.nan, fit.dtype),
                fit,
            )
        return fit, state.replace(inner=inner, fault_generation=gen + 1)
