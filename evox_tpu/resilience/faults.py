"""Deterministic fault injection for testing recovery paths on CPU.

:class:`FaultyProblem` wraps any :class:`~evox_tpu.core.Problem` and injects
faults by **evaluation schedule** (0-based evaluation index, counted in the
wrapper's own jitted state, so the schedule survives checkpoint/resume and
replays deterministically):

* **NaN rows** — the first ``nan_rows`` fitness entries of scheduled
  evaluations become NaN *inside the jitted program*, exercising the
  workflow's non-finite quarantine without leaving XLA.
* **Inf rows** — same, with ``+inf`` (``inf_generations``/``inf_rows``):
  overflow-style divergence, the other half of the quarantine contract.
* **in-state corruption** — scheduled evaluations write NaN into a
  dedicated ``corruption`` leaf of the wrapper's own jitted state
  (``corrupt_generations``): a fitness-independent degenerate-state
  signature the quarantine cannot mask, for
  :class:`~evox_tpu.resilience.HealthProbe`'s non-finite-state detector.
  Like the host-exception faults, corruption is **attempt-counted on the
  host** (``corrupt_times``): a restart that rolls the evaluation index
  back and replays sees the corruption as "over" — the leaf is recomputed
  every evaluation, so the replay heals it and restart policies can
  demonstrate recovery.
* **stagnation plateaus** — fitness is clamped to ``plateau_floor`` for
  every evaluation in ``[plateau_from, plateau_until)``: the best fitness
  cannot improve during the window, driving the probe's stagnation
  detector.
* **dead shards** — mesh-position-keyed NaN rows
  (``dead_shards={shard: (eval indices)}``): every fitness row belonging to
  the scheduled shard's contiguous row block goes NaN, modeling one device
  of the mesh returning garbage while the all-gather still "succeeds" — the
  exact failure the workflow's shard-granular quarantine
  (``StdWorkflow(quarantine_granularity="shard")``) and the health probe's
  dead-shard verdict exist for.  Wrap the ``ShardedProblem`` (fault OUTSIDE
  the shard_map) so the schedule state advances with the replicated program
  and rows are addressed globally.
* **straggler shards** — mesh-position-keyed host delays
  (``straggler_shards={shard: (eval indices)}``): the host callback sleeps
  ``straggler_delay`` seconds, which stalls the whole step exactly the way
  one slow device stalls a real all-gather.  Attempt-counted per
  ``(shard, eval)`` like the other host faults.
* **eval deadline** — with ``eval_deadline`` set, the host-fault callback
  (delays, stragglers, injected errors) runs under a wall-clock deadline:
  if it does not finish in time, the evaluation is *abandoned* — every
  fitness row of that evaluation becomes ``deadline_penalty`` (NaN by
  default, flowing straight into the workflow's quarantine) and the run
  continues, instead of wedging the program until the supervisor's watchdog
  shoots it.  The penalty-fallback contract for host-callback problems.
* **host-side exceptions** — an ``io_callback`` raises
  :class:`InjectedBackendError` (message carries ``UNAVAILABLE``, the
  BASELINE.md outage signature); XLA wraps it into the same
  ``XlaRuntimeError: INTERNAL: CpuCallback error`` a real backend loss
  produces, so the runner's retry predicate sees exactly what production
  would show it.  :class:`InjectedFatalError` carries the ``NONRETRYABLE``
  marker instead — it simulates a genuine crash/process kill that retry must
  NOT paper over.
* **artificial delays** — the host callback sleeps, driving the runner's
  watchdog path (the silent-hang signature).
* **SIGTERM to self** — scheduled evaluations send the process a real
  ``SIGTERM`` (``sigterm_generations``), the way a cluster scheduler or TPU
  preemption actually kills a job.  Only meaningful under an installed
  :class:`~evox_tpu.resilience.PreemptionGuard` — without one the default
  handler terminates the test process.
* **fleet chaos** — process-keyed faults for ``jax.distributed`` multi-host
  runs: ``kill_process_at`` SIGKILLs the scheduled host outright (host
  death — survivors wedge in their next collective),
  ``partition_process_at`` freezes the scheduled host's progress while its
  liveness heartbeat keeps beating (coordinator partition / wedged host),
  and ``slow_process_at`` makes one host chronically slow (the cross-host
  straggler; under ``eval_deadline`` each injected sleep is *abandoned*
  after the deadline — fitness values are never altered, the collective
  just keeps moving — and counted in the host-side ``deadline_trips``,
  the per-host verdict a :class:`~evox_tpu.resilience.FleetSupervisor`
  reads through the heartbeat plane).

* **tenant-keyed lane faults** — ``lane_faults={lane_id: {...}}``: per-lane
  NaN/Inf rows, stagnation plateaus, and host delays that fire only for the
  pack lane whose ``fault_lane`` state leaf matches (the multi-tenant
  service writes each tenant's uid there at admission).  The chaos mode the
  service layer's bulkhead tests drive: one tenant's scheduled faults,
  cotenants untouched.

The **whole fault plan is audited at construction**: negative indices,
unknown per-lane fields, inverted plateau windows, out-of-range shard ids,
and contradictory fleet schedules (a SIGKILLed process also scheduled to
wedge) raise a ``ValueError`` naming the field — never a silent no-op or a
shape error deep inside jit.  The full fault matrix is tabulated in
``docs/guide/resilience.md``.

Transient faults are **attempt-counted on the host side**: a fault fires for
its first ``*_times`` attempts of a given evaluation index and then stops,
modeling an outage that passes — which is what lets retry/resume tests
complete.  Counters live on the wrapper instance (host memory), not in the
jitted state: a retry that reloads the checkpoint rolls the evaluation index
back but must still see the outage as "over".

:class:`FaultyStore` is the storage-side counterpart: a
:class:`~evox_tpu.utils.CheckpointStore` that injects torn publishes, bit
flips, ``ENOSPC``/``EIO``, crash-between-temp-and-rename, and slow disks by
**save schedule** (0-based count of ``save_state`` calls through the
store), so the whole checkpoint pipeline — async writer, GC ordering,
verify/quarantine on resume, mid-write preemption — is testable
deterministically on any filesystem.
"""

from __future__ import annotations

import errno
import os
import signal
import threading
import time
from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback
from jax.sharding import SingleDeviceSharding

from ..core import Problem, State
from ..utils.checkpoint import CheckpointStore
from .schedule import validate_schedule

__all__ = [
    "FaultyProblem",
    "FaultyStore",
    "InjectedBackendError",
    "InjectedFatalError",
    "InjectedStorageError",
    "validate_schedule",
]


class InjectedBackendError(RuntimeError):
    """Simulated transient backend loss (retryable signature)."""


class InjectedFatalError(RuntimeError):
    """Simulated unrecoverable crash (carries the NONRETRYABLE marker)."""


class InjectedStorageError(OSError):
    """Simulated storage failure (crash between temp write and publish)."""


class FaultyProblem(Problem):
    """Wraps a problem with a deterministic, generation-scheduled fault plan.

    The wrapper is numerically transparent (same fitness, no extra PRNG use)
    — host faults raise/sleep but never touch the data path, and NaN
    injection only fires on scheduled evaluations.  For bit-identical
    clean-run comparators, keep the *program structure* identical too: build
    the comparator with the SAME schedule but ``*_times=0`` (the host
    callback stays in the compiled program — XLA fusion, and therefore
    ulp-level float results, can differ between programs with and without
    the callback op).
    """

    def __init__(
        self,
        problem: Problem,
        *,
        nan_generations: Sequence[int] = (),
        nan_rows: int = 1,
        inf_generations: Sequence[int] = (),
        inf_rows: int = 1,
        corrupt_generations: Sequence[int] = (),
        corrupt_times: int = 1,
        plateau_from: int | None = None,
        plateau_until: int | None = None,
        plateau_floor: float = 1.0,
        error_generations: Sequence[int] = (),
        error_times: int = 1,
        error_message: str = "UNAVAILABLE: injected backend loss (fault schedule)",
        fatal_generations: Sequence[int] = (),
        fatal_times: int = 1,
        delay_generations: Sequence[int] = (),
        delay_seconds: float = 1.0,
        delay_times: int = 1,
        sigterm_generations: Sequence[int] = (),
        sigterm_times: int = 1,
        dead_shards: Mapping[int, Sequence[int]] | None = None,
        straggler_shards: Mapping[int, Sequence[int]] | None = None,
        straggler_delay: float = 1.0,
        straggler_times: int = 1,
        shards: int | None = None,
        eval_deadline: float | None = None,
        deadline_penalty: float = float("nan"),
        kill_process_at: Mapping[int, Sequence[int]] | None = None,
        kill_times: int = 1,
        partition_process_at: Mapping[int, Sequence[int]] | None = None,
        partition_seconds: float = 3600.0,
        partition_times: int = 1,
        slow_process_at: Mapping[int, Sequence[int]] | None = None,
        slow_process_seconds: float = 1.0,
        slow_process_times: int = 1,
        lane_faults: Mapping[int, Mapping[str, Any]] | None = None,
    ):
        """
        :param nan_generations: evaluation indices whose fitness gets NaN
            injected into its first ``nan_rows`` rows (inside jit).
        :param inf_generations: evaluation indices whose fitness gets
            ``+inf`` injected into its first ``inf_rows`` rows (inside
            jit) — overflow-style divergence for the quarantine's Inf path.
        :param corrupt_generations: evaluation indices whose evaluation
            writes NaN into the wrapper's own ``corruption`` state leaf —
            in-state corruption the fitness quarantine cannot see, for the
            health probe's non-finite-state detector.  Fires for the first
            ``corrupt_times`` attempts of each index (host-counted, like
            the exception faults), and the leaf is recomputed every
            evaluation — so a restart that replays (rollback) or continues
            past the schedule (reinit/perturb) heals it.
        :param plateau_from: first evaluation index (inclusive) of a
            stagnation plateau: fitness is clamped to at least
            ``plateau_floor`` while the plateau lasts, so the best fitness
            cannot improve.  ``None`` disables.
        :param plateau_until: end of the plateau (exclusive); ``None``
            with ``plateau_from`` set means "until the run ends".
        :param plateau_floor: the clamp value during the plateau.
        :param error_generations: evaluation indices that raise a retryable
            :class:`InjectedBackendError` from the host, for the first
            ``error_times`` attempts each.
        :param fatal_generations: evaluation indices that raise a
            ``NONRETRYABLE`` :class:`InjectedFatalError` for the first
            ``fatal_times`` attempts each (simulated kill; a supervisor
            must surface it, and a later resume gets past it).
        :param delay_generations: evaluation indices whose host callback
            sleeps ``delay_seconds`` for the first ``delay_times`` attempts
            each (watchdog fodder).
        :param sigterm_generations: evaluation indices that send the
            process a real ``SIGTERM`` (``os.kill`` to self) for the first
            ``sigterm_times`` attempts each — the scheduler-kill /
            TPU-preemption signature, for exercising
            :class:`~evox_tpu.resilience.PreemptionGuard`'s graceful path.
            **Install a guard first**: without one, the default handler
            terminates the process on the spot.
        :param dead_shards: ``{shard_index: evaluation indices}`` — every
            fitness row in the scheduled shard's contiguous row block goes
            NaN (inside jit), modeling one mesh device returning garbage
            through a "successful" all-gather.  Wrap this around the
            ``ShardedProblem`` so rows are addressed globally.
        :param straggler_shards: ``{shard_index: evaluation indices}`` —
            the host callback sleeps ``straggler_delay`` seconds for the
            first ``straggler_times`` attempts of each ``(shard, eval)``
            pair, stalling the step the way one slow device stalls a real
            all-gather.
        :param shards: shard count for the row-block mapping of
            ``dead_shards``; defaults to the mesh axis size of a
            ``ShardedProblem`` found on the wrapped problem chain.
        :param eval_deadline: wall-clock seconds the host-fault callback
            may take; past it the evaluation is abandoned — all fitness
            rows become ``deadline_penalty`` and the run continues (the
            penalty fallback for host-callback problems).  ``None``
            (default) leaves host faults unguarded: delays stall the
            program until the supervisor's watchdog intervenes.
        :param deadline_penalty: fitness value substituted for a deadlined
            evaluation (default NaN, so the workflow quarantine penalizes
            and counts it).
        :param kill_process_at: ``{process_index: evaluation indices}`` —
            **fleet chaos**: the scheduled process sends itself a real
            ``SIGKILL`` (no handler, no cleanup, no goodbye) for the first
            ``kill_times`` attempts of each index, modeling host death /
            OOM-kill / pod loss mid-run.  Keyed on ``jax.process_index()``
            read on the host, so only the scheduled member of a
            ``jax.distributed`` fleet dies; single-process runs die only
            when index 0 is scheduled.  Survivors wedge in their next
            collective — exactly the production signature a
            :class:`~evox_tpu.resilience.FleetSupervisor` exists to
            detect.  A relaunched worker constructs a NEW wrapper (attempt
            counters are per-process memory), so key the schedule on the
            supervisor's attempt number to model "the bad host left the
            pool".
        :param partition_process_at: ``{process_index: evaluation
            indices}`` — fleet chaos: the scheduled process's host
            callback sleeps ``partition_seconds`` (default: an hour — in
            practice, forever), for the first ``partition_times`` attempts
            of each index.  Models a network partition from the
            coordinator / a wedged host: the process stays alive (its
            heartbeat liveness thread keeps beating) while its generation
            progress freezes — the supervisor's **wedged** verdict, as
            opposed to the **dead** one.
        :param slow_process_at: ``{process_index: evaluation indices}`` —
            fleet chaos: the scheduled process's host callback sleeps
            ``slow_process_seconds`` for the first ``slow_process_times``
            attempts of each ``(process, eval)`` — one chronically slow
            host stalling every peer's collective, the cross-host
            straggler.  Combine with ``eval_deadline`` to exercise the
            quarantine path: the deadline *abandons* each injected sleep
            (unlike the host-fault channel there is no penalty-row
            substitution — fitness values are never altered, the
            collective just keeps moving after at most ``eval_deadline``
            seconds) and bumps the worker's ``deadline_trips`` counter,
            which — surfaced through its heartbeat — feeds the
            supervisor's per-host **slow** verdict.
        :param lane_faults: ``{lane_id: {field: value}}`` — **tenant-keyed
            chaos** for multi-tenant packs (``evox_tpu.service``): faults
            that fire only for the pack lane whose ``fault_lane`` state
            leaf matches ``lane_id`` (the service writes each tenant's
            stable uid into its lane at admission; unpacked runs carry the
            ``-1`` sentinel and match nothing).  Per-lane fields:
            ``nan_generations``/``nan_rows``,
            ``inf_generations``/``inf_rows``,
            ``plateau_from``/``plateau_until``/``plateau_floor``
            (all in-jit, so they vmap over the lane axis and replay
            deterministically), and
            ``delay_generations``/``delay_seconds``/``delay_times``
            (host callback keyed on the lane payload, attempt-counted per
            ``(lane, eval)``).  Unknown fields are rejected at
            construction — the whole fault plan is audited by one
            validation pass (see the class docstring).
        """
        self.problem = problem
        self.nan_generations = tuple(int(g) for g in nan_generations)
        self.nan_rows = int(nan_rows)
        self.inf_generations = tuple(int(g) for g in inf_generations)
        self.inf_rows = int(inf_rows)
        self.corrupt_generations = frozenset(
            int(g) for g in corrupt_generations
        )
        self.corrupt_times = int(corrupt_times)
        self.plateau_from = None if plateau_from is None else int(plateau_from)
        self.plateau_until = (
            None if plateau_until is None else int(plateau_until)
        )
        self.plateau_floor = float(plateau_floor)
        self.error_generations = frozenset(int(g) for g in error_generations)
        self.error_times = int(error_times)
        self.error_message = error_message
        self.fatal_generations = frozenset(int(g) for g in fatal_generations)
        self.fatal_times = int(fatal_times)
        self.delay_generations = frozenset(int(g) for g in delay_generations)
        self.delay_seconds = float(delay_seconds)
        self.delay_times = int(delay_times)
        self.sigterm_generations = frozenset(
            int(g) for g in sigterm_generations
        )
        self.sigterm_times = int(sigterm_times)
        self.dead_shards = tuple(
            (int(s), tuple(int(g) for g in gens))
            for s, gens in sorted((dead_shards or {}).items())
        )
        self.straggler_shards = {
            int(s): frozenset(int(g) for g in gens)
            for s, gens in (straggler_shards or {}).items()
        }
        self.straggler_delay = float(straggler_delay)
        self.straggler_times = int(straggler_times)
        self.shards = None if shards is None else int(shards)
        if self.dead_shards and self._n_shards() is None:
            raise ValueError(
                "dead_shards needs the shard count to map shards to row "
                "blocks: wrap a ShardedProblem (auto-detected) or pass "
                "shards=N explicitly"
            )
        self.eval_deadline = (
            None if eval_deadline is None else float(eval_deadline)
        )
        self.deadline_penalty = float(deadline_penalty)
        self.kill_process_at = {
            int(p): frozenset(int(g) for g in gens)
            for p, gens in (kill_process_at or {}).items()
        }
        self.kill_times = int(kill_times)
        self.partition_process_at = {
            int(p): frozenset(int(g) for g in gens)
            for p, gens in (partition_process_at or {}).items()
        }
        self.partition_seconds = float(partition_seconds)
        self.partition_times = int(partition_times)
        self.slow_process_at = {
            int(p): frozenset(int(g) for g in gens)
            for p, gens in (slow_process_at or {}).items()
        }
        self.slow_process_seconds = float(slow_process_seconds)
        self.slow_process_times = int(slow_process_times)
        self.lane_faults = self._normalize_lane_faults(lane_faults or {})
        # Host-side count of eval-deadline expiries on THIS process — the
        # per-host straggler self-report a worker surfaces through its
        # heartbeat payload so the fleet supervisor can render a per-host
        # slow verdict (multi-host straggler quarantine).
        self.deadline_trips = 0
        # Set by StdWorkflow when this wrapper ends up sharing a program
        # with a shard_map it cannot see from its own chain (the
        # enable_distributed auto-wrap puts the ShardedProblem ABOVE us):
        # ordered callbacks must then be avoided (see _callback_kwargs).
        self.in_sharded_program = False
        # Set (at trace time) by the workflow's fused-segment builder: the
        # evaluation is the body of a multi-generation lax.scan, where an
        # ordered callback would serialize the scan against the host — and
        # is unsupported under the vmapped/early-stop program shapes.
        # Fault semantics are unaffected (attempt counters key on the
        # evaluation index in the payload, never on arrival order).
        self.in_fused_program = False
        self._lock = threading.Lock()
        self._attempts: dict[tuple[str, int], int] = {}
        self._has_host_faults = bool(
            self.error_generations
            or self.fatal_generations
            or self.delay_generations
            or self.sigterm_generations
            or self.straggler_shards
        )
        # Lane-keyed host delays ride their own callback (it carries the
        # lane id in the payload, which the shared host hook does not).
        self._has_lane_host_faults = any(
            spec["delay_generations"] for spec in self.lane_faults.values()
        )
        # Fleet (process-keyed) faults ride a separate callback channel:
        # a plain callback only executes on process 0's host in a
        # multi-process program, so these dispatch through a shard_map'd
        # callback that fires on every process (see evaluate).  Presence is
        # keyed on the SCHEDULE, not the times, so a ``*_times=0``
        # comparator run compiles the identical program.
        self._has_fleet_faults = bool(
            self.kill_process_at
            or self.partition_process_at
            or self.slow_process_at
        )
        # One validation point for the whole fault plan: the schedule
        # surface has grown a field or two per PR, and a typo'd index or a
        # contradictory pair used to surface as a silent no-op (or a shape
        # error deep inside jit) instead of a constructor error.
        self._validate_schedules()

    # -- construction-time schedule audit -----------------------------------
    _LANE_FAULT_FIELDS = {
        "nan_generations": (),
        "nan_rows": 1,
        "inf_generations": (),
        "inf_rows": 1,
        "plateau_from": None,
        "plateau_until": None,
        "plateau_floor": 1.0,
        "delay_generations": (),
        "delay_seconds": 1.0,
        "delay_times": 1,
    }

    def _normalize_lane_faults(
        self, lane_faults: Mapping[int, Mapping[str, Any]]
    ) -> dict[int, dict[str, Any]]:
        out: dict[int, dict[str, Any]] = {}
        for lane, spec in sorted(lane_faults.items()):
            unknown = sorted(set(spec) - set(self._LANE_FAULT_FIELDS))
            if unknown:
                raise ValueError(
                    f"lane_faults[{lane}] has unknown fault field(s) "
                    f"{unknown}; valid per-lane fields are "
                    f"{sorted(self._LANE_FAULT_FIELDS)}"
                )
            full = {
                k: spec.get(k, default)
                for k, default in self._LANE_FAULT_FIELDS.items()
            }
            out[int(lane)] = {
                "nan_generations": tuple(
                    int(g) for g in full["nan_generations"]
                ),
                "nan_rows": int(full["nan_rows"]),
                "inf_generations": tuple(
                    int(g) for g in full["inf_generations"]
                ),
                "inf_rows": int(full["inf_rows"]),
                "plateau_from": (
                    None
                    if full["plateau_from"] is None
                    else int(full["plateau_from"])
                ),
                "plateau_until": (
                    None
                    if full["plateau_until"] is None
                    else int(full["plateau_until"])
                ),
                "plateau_floor": float(full["plateau_floor"]),
                "delay_generations": frozenset(
                    int(g) for g in full["delay_generations"]
                ),
                "delay_seconds": float(full["delay_seconds"]),
                "delay_times": int(full["delay_times"]),
            }
        return out

    def _validate_schedules(self) -> None:
        """Reject malformed or self-contradictory fault plans loudly, at
        construction — the single audit point for every schedule field the
        wrapper has grown (the full matrix is tabulated in
        ``docs/guide/resilience.md``)."""

        def gens(name: str, values) -> None:
            bad = [g for g in values if g < 0]
            if bad:
                raise ValueError(
                    f"{name} schedules 0-based evaluation indices; got "
                    f"negative index(es) {sorted(bad)}"
                )

        def nonneg(name: str, value) -> None:
            if value < 0:
                raise ValueError(f"{name} must be >= 0, got {value}")

        gens("nan_generations", self.nan_generations)
        gens("inf_generations", self.inf_generations)
        gens("corrupt_generations", self.corrupt_generations)
        gens("error_generations", self.error_generations)
        gens("fatal_generations", self.fatal_generations)
        gens("delay_generations", self.delay_generations)
        gens("sigterm_generations", self.sigterm_generations)
        for name, count in (
            ("nan_rows", self.nan_rows),
            ("inf_rows", self.inf_rows),
            ("corrupt_times", self.corrupt_times),
            ("error_times", self.error_times),
            ("fatal_times", self.fatal_times),
            ("delay_times", self.delay_times),
            ("sigterm_times", self.sigterm_times),
            ("straggler_times", self.straggler_times),
            ("kill_times", self.kill_times),
            ("partition_times", self.partition_times),
            ("slow_process_times", self.slow_process_times),
            ("delay_seconds", self.delay_seconds),
            ("straggler_delay", self.straggler_delay),
            ("partition_seconds", self.partition_seconds),
            ("slow_process_seconds", self.slow_process_seconds),
        ):
            nonneg(name, count)
        for name, frm, until in [
            ("plateau", self.plateau_from, self.plateau_until)
        ] + [
            (f"lane_faults[{lane}] plateau", s["plateau_from"], s["plateau_until"])
            for lane, s in self.lane_faults.items()
        ]:
            if until is not None and frm is None:
                raise ValueError(
                    f"{name}_until without {name}_from: a plateau window "
                    f"needs its start (plateau_from=N)"
                )
            if frm is not None and frm < 0:
                raise ValueError(f"{name}_from must be >= 0, got {frm}")
            if until is not None and frm is not None and until < frm:
                raise ValueError(
                    f"{name}_until ({until}) must be >= {name}_from ({frm}) "
                    f"— the window is [from, until)"
                )
        n_shards = self._n_shards()
        for name, shard_map_ in (
            ("dead_shards", dict(self.dead_shards)),
            ("straggler_shards", self.straggler_shards),
        ):
            for shard, shard_gens in shard_map_.items():
                gens(f"{name}[{shard}]", shard_gens)
                if shard < 0:
                    raise ValueError(
                        f"{name} keys are mesh shard indices; got {shard}"
                    )
                if n_shards is not None and shard >= n_shards:
                    raise ValueError(
                        f"{name} schedules shard {shard}, but the "
                        f"evaluation runs on {n_shards} shard(s) "
                        f"(indices 0..{n_shards - 1}) — a fault that can "
                        f"never fire is a misconfigured test, not chaos"
                    )
        if self.eval_deadline is not None and self.eval_deadline <= 0:
            raise ValueError(
                f"eval_deadline must be > 0 seconds, got {self.eval_deadline}"
            )
        for name, proc_map in (
            ("kill_process_at", self.kill_process_at),
            ("partition_process_at", self.partition_process_at),
            ("slow_process_at", self.slow_process_at),
        ):
            for proc, proc_gens in proc_map.items():
                if proc < 0:
                    raise ValueError(
                        f"{name} keys are jax.process_index() values; "
                        f"got {proc}"
                    )
                gens(f"{name}[{proc}]", proc_gens)
        # A process SIGKILLed at (proc, eval) cannot also wedge or slow
        # there: the overlap means the plan's author expected two
        # different fates for one host at one moment.
        for proc, kill_gens in self.kill_process_at.items():
            for other_name, other in (
                ("partition_process_at", self.partition_process_at),
                ("slow_process_at", self.slow_process_at),
            ):
                overlap = kill_gens & other.get(proc, frozenset())
                if overlap:
                    raise ValueError(
                        f"conflicting fleet schedules for process {proc}: "
                        f"kill_process_at and {other_name} both fire at "
                        f"evaluation(s) {sorted(overlap)} — a SIGKILLed "
                        f"process cannot also be wedged/slowed"
                    )
        for lane, spec in self.lane_faults.items():
            if lane < 0:
                raise ValueError(
                    f"lane_faults keys are stable lane/tenant ids >= 0 "
                    f"(-1 is the unassigned sentinel); got {lane}"
                )
            gens(f"lane_faults[{lane}].nan_generations", spec["nan_generations"])
            gens(f"lane_faults[{lane}].inf_generations", spec["inf_generations"])
            gens(
                f"lane_faults[{lane}].delay_generations",
                spec["delay_generations"],
            )
            for fname in (
                "nan_rows",
                "inf_rows",
                "delay_times",
                "delay_seconds",
            ):
                nonneg(f"lane_faults[{lane}].{fname}", spec[fname])

    def _mesh_in_chain(self) -> int | None:
        """Mesh axis size of a ShardedProblem on the wrapped chain, if any
        (the shared ``parallel.find_sharded`` walk)."""
        from ..parallel import find_sharded

        sharded = find_sharded(self.problem)
        if sharded is None:
            return None
        return int(sharded.mesh.shape[sharded.axis_name])

    def _n_shards(self) -> int | None:
        """Shard count for row-block mapping: explicit ``shards`` wins, else
        the mesh axis size of a ShardedProblem on the wrapped chain."""
        if self.shards is not None:
            return self.shards
        return self._mesh_in_chain()

    def _callback_kwargs(self) -> dict:
        """io_callback flavor for the host-fault side channel.

        Unsharded programs use ``ordered=True`` pinned to one device —
        exactly-once, in program order, like a real backend fault.  Programs
        containing a ``shard_map`` must use UNORDERED callbacks instead: an
        ordered callback threads a token through the entry computation, and
        jax 0.4.x XLA's SPMD sharding-propagation options are sized without
        the token parameter — the compiler hard-aborts (Check failed:
        sharding_propagation.cc).  Same contract as the monitor side channel
        (``workflows/eval_monitor.py``); fault semantics are unaffected —
        attempt counters key on the evaluation index carried in the payload,
        never on arrival order.  The shard_map may sit BELOW this wrapper
        (``_mesh_in_chain``) or ABOVE it (``in_sharded_program``, set by the
        workflow's enable_distributed auto-wrap); in the latter case the
        callback traces inside the shard_map body and fires once per shard,
        so attempt counts scale by the shard count — wrap the
        ``ShardedProblem`` yourself (fault outside) for exactly-once
        semantics.  Fused multi-generation segments
        (``StdWorkflow.run_segment``) also force unordered callbacks — the
        scan body fires once per generation inside one compiled program,
        and an ordered callback would serialize it against the host (see
        ``in_fused_program``)."""
        if (
            self._mesh_in_chain() is not None
            or self.in_sharded_program
            or self.in_fused_program
        ):
            return {"ordered": False}
        return {
            "ordered": True,
            "sharding": SingleDeviceSharding(jax.local_devices()[0]),
        }

    # -- pickling ----------------------------------------------------------
    # A fault plan must survive pickling: the serving daemon journals
    # every TenantSpec (problem included) to make submissions durable,
    # and chaos tenants are exactly the specs the kill-restart tests
    # resubmit.  The attempt-counter lock is process-local, and the
    # counters themselves are host-side observation state — a spec
    # restored in a fresh process re-arms them, which is the fresh-
    # process semantics anyway.
    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        state["_attempts"] = {}
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    # -- host side ---------------------------------------------------------
    def _bump(self, kind: str, gen: int) -> int:
        with self._lock:
            n = self._attempts.get((kind, gen), 0) + 1
            self._attempts[(kind, gen)] = n
            return n

    def attempts(self, kind: str, gen: int) -> int:
        """How many times the ``kind`` fault at evaluation ``gen`` has been
        reached so far (test observability)."""
        with self._lock:
            return self._attempts.get((kind, gen), 0)

    def reset_faults(self) -> None:
        """Forget all attempt counts (faults re-arm)."""
        with self._lock:
            self._attempts.clear()
            self.deadline_trips = 0

    def _corrupt_flag(self, gen) -> np.bool_:
        """Host side of the corruption schedule: True while the fault is
        live for this evaluation index (first ``corrupt_times`` attempts)."""
        g = int(gen)
        if g in self.corrupt_generations:
            if self._bump("corrupt", g) <= self.corrupt_times:
                return np.bool_(True)
        return np.bool_(False)

    def _host_hook(self, gen) -> None:
        g = int(gen)
        if g in self.fatal_generations:
            if self._bump("fatal", g) <= self.fatal_times:
                raise InjectedFatalError(
                    f"NONRETRYABLE: injected unrecoverable crash at "
                    f"evaluation {g} (simulated process kill)"
                )
        if g in self.error_generations:
            if self._bump("error", g) <= self.error_times:
                raise InjectedBackendError(f"{self.error_message} [eval {g}]")
        if g in self.sigterm_generations:
            if self._bump("sigterm", g) <= self.sigterm_times:
                # A real signal to the real process: exactly what a
                # scheduler's grace-window kill delivers.  The evaluation
                # itself continues — the PreemptionGuard's flag is checked
                # at the next segment boundary, not mid-program.
                os.kill(os.getpid(), signal.SIGTERM)
        if g in self.delay_generations:
            if self._bump("delay", g) <= self.delay_times:
                time.sleep(self.delay_seconds)
        for shard, gens in self.straggler_shards.items():
            if g in gens:
                if self._bump(f"straggler{shard}", g) <= self.straggler_times:
                    # One slow shard stalls the whole step, exactly like a
                    # straggler device stalls the all-gather barrier.
                    time.sleep(self.straggler_delay)

    def _lane_host_hook(self, gen, lane) -> None:
        """Host side of the lane-keyed delay faults: sleeps only when THIS
        payload's lane has a scheduled delay, attempt-counted per
        ``(lane, eval)``.  Under a vmapped pack the unordered callback
        fires once per lane, each carrying its own lane id — a slow
        tenant stalls the pack's step exactly like a slow tenant would
        stall a shared accelerator (the pack-level stall is the fault
        being modeled; the bulkhead contract is about *values*, which the
        sleep never touches)."""
        g, l = int(gen), int(lane)
        spec = self.lane_faults.get(l)
        if spec is None or g not in spec["delay_generations"]:
            return
        if self._bump(f"lane_delay{l}", g) <= spec["delay_times"]:
            time.sleep(spec["delay_seconds"])

    def _fleet_hook(self, gen) -> None:
        """Host side of the process-keyed fleet faults.

        Fires on EVERY process's host (see the shard-mapped dispatch in
        :meth:`evaluate` — a plain callback only executes on process 0 in a
        multi-process program); only the scheduled ``jax.process_index()``
        acts.  Reached once per *local shard* per evaluation, so the
        ``*_times`` attempt counters absorb the multiplicity: times=1 means
        "once per evaluation index", however many local shards bump it."""
        g = int(gen)
        proc = int(jax.process_index())
        if g in self.kill_process_at.get(proc, ()):
            if self._bump(f"kill{proc}", g) <= self.kill_times:
                # A real SIGKILL to the real process: no handler runs, no
                # checkpoint flushes — host death, the failure the fleet
                # supervisor exists for.
                os.kill(os.getpid(), signal.SIGKILL)
        if g in self.partition_process_at.get(proc, ()):
            if self._bump(f"partition{proc}", g) <= self.partition_times:
                # Alive but unreachable: generation progress freezes while
                # the liveness heartbeat keeps beating — the wedged-host
                # (coordinator partition) signature.
                time.sleep(self.partition_seconds)
        if g in self.slow_process_at.get(proc, ()):
            if self._bump(f"slowproc{proc}", g) <= self.slow_process_times:
                # One chronically slow host stalls every peer's collective
                # (the cross-host straggler); under eval_deadline the sleep
                # runs inside the deadline guard and is abandoned to
                # penalty rows + a deadline_trips bump instead.
                if self.eval_deadline is not None:
                    self._deadline_guarded(
                        lambda: time.sleep(self.slow_process_seconds)
                    )
                else:
                    time.sleep(self.slow_process_seconds)

    def _deadline_guarded(self, fn) -> bool:
        """Run ``fn()`` in an abandoned-on-timeout daemon worker; returns
        whether the eval deadline tripped.  A worker that finishes in time
        re-raises its exception (error faults keep their retry semantics);
        one that does not is left to die with its sleep.  Every trip is
        counted in ``deadline_trips`` — the per-host straggler self-report
        a worker surfaces through its heartbeat so the fleet supervisor
        can quarantine the slow host at a segment boundary."""
        result: dict = {}

        def target() -> None:
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 - re-raised below
                result["error"] = e

        worker = threading.Thread(
            target=target, name="evox-tpu-eval-deadline", daemon=True
        )
        worker.start()
        worker.join(self.eval_deadline)
        if worker.is_alive():
            with self._lock:
                self.deadline_trips += 1
            return True
        if "error" in result:
            raise result["error"]
        return False

    def _guarded_hook(self, gen) -> np.bool_:
        """``_host_hook`` under the eval deadline: the evaluation falls
        back to the penalty when the deadline trips."""
        return np.bool_(self._deadline_guarded(lambda: self._host_hook(gen)))

    # -- component protocol ------------------------------------------------
    def setup(self, key: jax.Array) -> State:
        return State(
            inner=self.problem.setup(key),
            # 0-based evaluation index; lives in the jitted state so it is
            # checkpointed and rolls back with the run on resume.
            fault_generation=jnp.int32(0),
            # In-state corruption canary: NaN during scheduled evaluations
            # (``corrupt_generations``), healthy 0.0 otherwise.  Always
            # present (even with an empty schedule) so faulted runs and
            # their ``*_times=0`` comparators share one program structure.
            corruption=jnp.float32(0.0),
            # Stable lane/tenant identity for ``lane_faults`` — written by
            # the multi-tenant service at admission (tenant uid); the -1
            # sentinel matches no schedule, so unpacked runs are
            # untouched.  Always present so packed states and their solo
            # comparators share one structure.
            fault_lane=jnp.int32(-1),
        )

    def _inject_rows(
        self,
        fit: jax.Array,
        gen: jax.Array,
        schedule: tuple,
        rows: int,
        value,
        extra: jax.Array | None = None,
    ) -> jax.Array:
        scheduled = jnp.any(gen == jnp.asarray(schedule, jnp.int32))
        if extra is not None:
            scheduled = jnp.logical_and(scheduled, extra)
        row_mask = jnp.arange(fit.shape[0]) < rows
        mask = row_mask if fit.ndim == 1 else row_mask[:, None]
        return jnp.where(
            jnp.logical_and(scheduled, mask),
            jnp.asarray(value, fit.dtype),
            fit,
        )

    def _dispatch_fleet_hook(self, gen: jax.Array) -> None:
        """Trace the process-keyed fleet-fault callback so it fires on
        EVERY process's host.

        A plain (unsharded) callback op executes only on process 0 in a
        multi-process program — a kill scheduled for process 2 would never
        fire.  When a mesh is on the WRAPPED chain (below us — so this
        evaluate traces outside any shard body) and the fleet is real
        (``process_count > 1``), the hook is traced inside a trivial
        ``shard_map`` over that mesh, so each process's local shards invoke
        it on their own host (the ``*_times`` counters absorb the
        per-shard multiplicity).  Inside an ``enable_distributed``
        auto-wrap the mesh is ABOVE us — evaluate() already traces in the
        shard body, so the plain unordered callback fires per shard on
        every process; single-process programs have only one host.  Note
        ``in_sharded_program`` cannot discriminate here: it is set whenever
        the program contains a shard_map *anywhere* (it governs callback
        ordering, not placement) — below-the-wrapper meshes set it too."""
        from ..parallel import find_sharded

        sharded = find_sharded(self.problem)
        # Sanctioned GL007 site: process_count() is FLEET-UNIFORM (the same
        # value on every host), so this trace-time branch picks the same
        # callback placement on every process — no divergent tracing.  The
        # rule exists for process_index()-style branches, which do differ.
        if sharded is not None and int(jax.process_count()) > 1:  # graftlint: disable=GL007
            from jax.sharding import PartitionSpec as P

            from ..parallel.sharded_problem import _CHECK_KW, _shard_map

            def _hook_shard(g):
                io_callback(self._fleet_hook, None, g, ordered=False)
                return g

            _shard_map(
                _hook_shard,
                mesh=sharded.mesh,
                in_specs=P(),
                out_specs=P(),
                **{_CHECK_KW: False},
            )(gen)
        else:
            io_callback(self._fleet_hook, None, gen, ordered=False)

    def evaluate(self, state: State, pop: jax.Array) -> tuple[jax.Array, State]:
        gen = state.fault_generation
        timed_out = None
        if self._has_fleet_faults:
            self._dispatch_fleet_hook(gen)
        if self._has_host_faults:
            # Ordered + pinned to one device: fires exactly once per
            # evaluation, in program order, like a real backend fault would.
            if self.eval_deadline is None:
                io_callback(self._host_hook, None, gen, **self._callback_kwargs())
            else:
                # Deadline-guarded: the callback reports a timeout instead
                # of stalling forever; the fitness falls back to the penalty
                # below and the run continues.
                timed_out = io_callback(
                    self._guarded_hook,
                    jax.ShapeDtypeStruct((), jnp.bool_),
                    gen,
                    **self._callback_kwargs(),
                )
        if self._has_lane_host_faults:
            io_callback(
                self._lane_host_hook,
                None,
                gen,
                state.fault_lane,
                **self._callback_kwargs(),
            )
        fit, inner = self.problem.evaluate(state.inner, pop)
        if self.nan_generations:
            fit = self._inject_rows(
                fit, gen, self.nan_generations, self.nan_rows, jnp.nan
            )
        if self.inf_generations:
            fit = self._inject_rows(
                fit, gen, self.inf_generations, self.inf_rows, jnp.inf
            )
        # Tenant-keyed lane faults: every schedule is masked on the state's
        # lane identity, so the program is one trace for the whole pack and
        # only the scheduled tenant's rows are touched (the bulkhead the
        # service tests lean on).
        for uid, spec in self.lane_faults.items():
            is_lane = state.fault_lane == jnp.int32(uid)
            if spec["nan_generations"]:
                fit = self._inject_rows(
                    fit,
                    gen,
                    spec["nan_generations"],
                    spec["nan_rows"],
                    jnp.nan,
                    extra=is_lane,
                )
            if spec["inf_generations"]:
                fit = self._inject_rows(
                    fit,
                    gen,
                    spec["inf_generations"],
                    spec["inf_rows"],
                    jnp.inf,
                    extra=is_lane,
                )
            if spec["plateau_from"] is not None:
                in_plateau = jnp.logical_and(
                    gen >= spec["plateau_from"], is_lane
                )
                if spec["plateau_until"] is not None:
                    in_plateau = jnp.logical_and(
                        in_plateau, gen < spec["plateau_until"]
                    )
                fit = jnp.where(
                    in_plateau,
                    jnp.maximum(
                        fit, jnp.asarray(spec["plateau_floor"], fit.dtype)
                    ),
                    fit,
                )
        if self.dead_shards:
            # Mesh-position-keyed NaN rows: the scheduled shard's whole
            # contiguous row block dies — the row→shard mapping is the
            # parallel layer's single definition (ragged tails included).
            from ..parallel import shard_row_ids

            row_shard = shard_row_ids(fit.shape[0], self._n_shards())
            for shard, gens in self.dead_shards:
                scheduled = jnp.any(gen == jnp.asarray(gens, jnp.int32))
                mask = jnp.logical_and(scheduled, row_shard == shard)
                mask = mask if fit.ndim == 1 else mask[:, None]
                fit = jnp.where(mask, jnp.asarray(jnp.nan, fit.dtype), fit)
        if timed_out is not None:
            # Deadline fallback: the whole evaluation is abandoned — every
            # row takes the penalty (NaN by default, so the workflow's
            # quarantine penalizes and counts it).
            fit = jnp.where(
                timed_out, jnp.asarray(self.deadline_penalty, fit.dtype), fit
            )
        if self.plateau_from is not None:
            in_plateau = gen >= self.plateau_from
            if self.plateau_until is not None:
                in_plateau = jnp.logical_and(
                    in_plateau, gen < self.plateau_until
                )
            # Clamp from below: nothing can beat the floor while the
            # plateau lasts, so the best fitness flatlines.
            fit = jnp.where(
                in_plateau,
                jnp.maximum(fit, jnp.asarray(self.plateau_floor, fit.dtype)),
                fit,
            )
        if self.corrupt_generations:
            # The live/over decision is host-counted (see class docstring);
            # the NaN write itself happens inside the jitted program, and
            # the leaf is recomputed per evaluation so replays heal it.
            corrupted = io_callback(
                self._corrupt_flag,
                jax.ShapeDtypeStruct((), jnp.bool_),
                gen,
                **self._callback_kwargs(),
            )
            corruption = jnp.where(
                corrupted, jnp.float32(jnp.nan), jnp.float32(0.0)
            )
        else:
            corruption = jnp.float32(0.0)
        return fit, state.replace(
            inner=inner, fault_generation=gen + 1, corruption=corruption
        )


class FaultyStore(CheckpointStore):
    """Deterministic storage chaos for the checkpoint pipeline.

    Wraps the :class:`~evox_tpu.utils.CheckpointStore` seam every
    ``save_state`` call flows through and injects faults by **save index**
    (0-based count of saves routed through this store instance), the same
    way :class:`FaultyProblem` schedules eval faults:

    * ``crash_saves`` — raise :class:`InjectedStorageError` *between* the
      completed temp write and the atomic rename: the classic
      kill-mid-checkpoint.  The destination is untouched (old checkpoint
      intact) and the temp file is cleaned up by ``save_state``.
    * ``torn_saves`` — publish a **truncated** final file (first
      ``torn_fraction`` of the bytes) *silently*: the signature of a
      non-atomic writer, or of a disk that acknowledged writes it lost to
      power failure.  Only ``verify_checkpoint`` / digest checks catch it.
    * ``flip_saves`` — publish normally, then flip a single bit in the
      final file (offset ``flip_offset``, default mid-file): bit rot that
      ``np.load`` reads back without complaint — the case SHA-256 leaf
      digests exist for.
    * ``enospc_saves`` / ``eio_saves`` — the archive write raises
      ``OSError`` with ``ENOSPC`` ("no space left on device") / ``EIO``;
      the checkpoint GC contract (never delete the predecessor before the
      successor is durably published) is tested with exactly this.
    * ``slow_saves`` — the archive write sleeps ``slow_seconds`` first
      (a congested or throttled disk), for async-writer overlap tests.

    Save indices count *attempts*: a save that faults still consumes its
    index, so "the next retry succeeds" schedules naturally.  ``saves``
    and ``unlinks`` expose what happened for test assertions; ``events``
    records one ``(index, kind)`` tuple per fired fault.
    """

    def __init__(
        self,
        *,
        crash_saves: Sequence[int] = (),
        torn_saves: Sequence[int] = (),
        torn_fraction: float = 0.5,
        flip_saves: Sequence[int] = (),
        flip_offset: int | None = None,
        enospc_saves: Sequence[int] = (),
        eio_saves: Sequence[int] = (),
        slow_saves: Sequence[int] = (),
        slow_seconds: float = 1.0,
    ):
        # Construction-time audit, the FaultyProblem discipline: negative
        # save indices and one save scheduled for two incompatible fates
        # (an aborted write — crash/ENOSPC/EIO — never publishes, so it
        # cannot also tear or bit-flip the published file) fail loudly
        # here, never lazily mid-run.
        schedules = validate_schedule(
            "FaultyStore",
            indices={
                "crash_saves": crash_saves,
                "torn_saves": torn_saves,
                "flip_saves": flip_saves,
                "enospc_saves": enospc_saves,
                "eio_saves": eio_saves,
                "slow_saves": slow_saves,
            },
            nonneg={
                "torn_fraction": float(torn_fraction),
                "slow_seconds": float(slow_seconds),
            },
            exclusive=[
                ("crash_saves", "enospc_saves"),
                ("crash_saves", "eio_saves"),
                ("enospc_saves", "eio_saves"),
                ("crash_saves", "torn_saves"),
                ("crash_saves", "flip_saves"),
                ("enospc_saves", "torn_saves"),
                ("enospc_saves", "flip_saves"),
                ("eio_saves", "torn_saves"),
                ("eio_saves", "flip_saves"),
            ],
        )
        self.crash_saves = schedules["crash_saves"]
        self.torn_saves = schedules["torn_saves"]
        self.torn_fraction = float(torn_fraction)
        self.flip_saves = schedules["flip_saves"]
        self.flip_offset = None if flip_offset is None else int(flip_offset)
        self.enospc_saves = schedules["enospc_saves"]
        self.eio_saves = schedules["eio_saves"]
        self.slow_saves = schedules["slow_saves"]
        self.slow_seconds = float(slow_seconds)
        self._lock = threading.Lock()
        self.saves = 0  # completed open_temp calls == save attempts
        self.unlinks: list[str] = []  # every file the caller deleted via us
        self.renames: list[tuple[str, str]] = []  # quarantine moves via us
        self.events: list[tuple[int, str]] = []
        self._current = -1  # save index of the attempt in progress

    def _fire(self, kind: str) -> None:
        with self._lock:
            self.events.append((self._current, kind))

    # -- the seam ----------------------------------------------------------
    def open_temp(self, directory, prefix):
        with self._lock:
            self._current = self.saves
            self.saves += 1
        return super().open_temp(directory, prefix)

    def write_archive(self, f, arrays):
        if self._current in self.slow_saves:
            self._fire("slow")
            time.sleep(self.slow_seconds)
        if self._current in self.enospc_saves:
            self._fire("enospc")
            raise OSError(
                errno.ENOSPC, "No space left on device (injected)"
            )
        if self._current in self.eio_saves:
            self._fire("eio")
            raise OSError(errno.EIO, "Input/output error (injected)")
        super().write_archive(f, arrays)

    def publish(self, tmp, final):
        if self._current in self.crash_saves:
            self._fire("crash")
            raise InjectedStorageError(
                f"injected crash between temp write and publish of {final} "
                f"(save #{self._current})"
            )
        if self._current in self.torn_saves:
            self._fire("torn")
            # Truncate the temp in place, then publish it: the final file
            # exists, opens, and is short — a lying-disk torn write.
            size = os.path.getsize(tmp)
            with open(tmp, "r+b") as tf:
                tf.truncate(max(1, int(size * self.torn_fraction)))
        super().publish(tmp, final)
        if self._current in self.flip_saves:
            self._fire("flip")
            size = os.path.getsize(final)
            offset = (
                self.flip_offset if self.flip_offset is not None else size // 2
            )
            with open(final, "r+b") as ff:
                ff.seek(offset)
                byte = ff.read(1)
                ff.seek(offset)
                ff.write(bytes([byte[0] ^ 0x01]))

    def write_bytes(self, f, data):
        # Raw-payload writes (the executable cache) share the archive
        # write's fault surface: the save index was assigned by the
        # open_temp that staged this temp file.
        if self._current in self.slow_saves:
            self._fire("slow")
            time.sleep(self.slow_seconds)
        if self._current in self.enospc_saves:
            self._fire("enospc")
            raise OSError(
                errno.ENOSPC, "No space left on device (injected)"
            )
        if self._current in self.eio_saves:
            self._fire("eio")
            raise OSError(errno.EIO, "Input/output error (injected)")
        super().write_bytes(f, data)

    def append_record(self, f, data):
        # Journal appends have no open_temp: each append consumes its own
        # save index, so "the third journal record is torn" schedules the
        # same way "the third checkpoint is torn" does.
        with self._lock:
            self._current = self.saves
            self.saves += 1
        if self._current in self.slow_saves:
            self._fire("slow")
            time.sleep(self.slow_seconds)
        if self._current in self.enospc_saves:
            self._fire("enospc")
            # Model a disk that accepted part of the record before filling
            # up: the torn prefix lands, then the OSError — exactly the
            # tail the replay's checksum discipline must skip.
            f.write(data[: max(1, len(data) // 3)])
            raise OSError(
                errno.ENOSPC, "No space left on device (injected)"
            )
        if self._current in self.eio_saves:
            self._fire("eio")
            raise OSError(errno.EIO, "Input/output error (injected)")
        if self._current in self.torn_saves:
            self._fire("torn")
            torn = data[: max(1, int(len(data) * self.torn_fraction))]
            f.write(torn)
            return len(torn)
        if self._current in self.flip_saves:
            self._fire("flip")
            offset = (
                self.flip_offset
                if self.flip_offset is not None
                else len(data) // 2
            ) % max(1, len(data))
            data = (
                data[:offset]
                + bytes([data[offset] ^ 0x01])
                + data[offset + 1 :]
            )
        return super().append_record(f, data)

    def unlink(self, path):
        self.unlinks.append(str(path))
        super().unlink(path)

    def rename(self, src, dst):
        self.renames.append((str(src), str(dst)))
        super().rename(src, dst)
