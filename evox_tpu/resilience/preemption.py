"""Signal-aware graceful shutdown for supervised runs.

``SIGTERM`` is how real schedulers kill jobs: TPU preemption, Kubernetes
pod eviction, SLURM time limits, and spot/preemptible reclamation all send
it with a grace window (typically 30 s) before the ``SIGKILL`` that nothing
survives.  Python's default handler turns ``SIGTERM`` into instant process
death — which, for a supervised run, loses every generation since the last
segment boundary and can land *mid-write* if a checkpoint was in flight.

:class:`PreemptionGuard` converts the signal into a cooperative flag.  The
:class:`~evox_tpu.resilience.ResilientRunner` checks the flag at every
segment boundary; when it trips, the runner barriers any in-flight async
checkpoint write, publishes an **emergency checkpoint** whose manifest
records ``preempted`` (and bumps the monitor's ``num_preemptions`` counter
in the saved state), restores the prior signal handlers, and raises
:class:`Preempted` — so the process exits cleanly inside the grace window
and the *next* invocation of the same two lines auto-resumes
bit-identically from the boundary the signal interrupted.

Cloud maintenance events that arrive out-of-band (GCE's metadata server,
a borg-style preemption notice file) plug in through ``provider_hook`` — a
zero-argument callable polled at the same boundaries; returning a truthy
value trips the guard exactly like a signal.  ``trip()`` trips it manually
(tests, custom integrations).

A guard is deliberately *two-strike*: the first signal is absorbed into
the flag (graceful path), but a second signal while the flag is already
set restores the original handlers and re-raises itself — repeated
``SIGTERM``/``Ctrl-C`` must always be able to kill a process that wedged
during its graceful shutdown.
"""

from __future__ import annotations

import signal
import threading
import warnings
from typing import Callable, Iterable, Union

__all__ = ["PreemptionGuard", "Preempted"]


class Preempted(RuntimeError):
    """The run was stopped cooperatively by a :class:`PreemptionGuard`.

    This is control flow, not a failure: when it reaches you, the emergency
    checkpoint is already durably on disk and re-running the same
    supervisor resumes bit-identically.  A top-level driver should catch it
    and exit 0 (or re-queue the job) — the scheduler's next incarnation of
    the process picks the run back up.

    :ivar generation: completed generations at the boundary that tripped.
    :ivar reason: what tripped the guard (e.g. ``"signal SIGTERM"``).
    :ivar checkpoint: path of the emergency checkpoint (``None`` only if
        the emergency write itself failed — the previous boundary
        checkpoint then remains the resume point).
    """

    def __init__(
        self,
        message: str,
        *,
        generation: int | None = None,
        reason: str | None = None,
        checkpoint=None,
    ):
        super().__init__(message)
        self.generation = generation
        self.reason = reason
        self.checkpoint = checkpoint


class PreemptionGuard:
    """Turns ``SIGTERM``/``SIGINT`` (and provider maintenance events) into
    a flag the run supervisor polls at segment boundaries.

    Usage — explicit, around anything::

        guard = PreemptionGuard()
        with guard:                       # install handlers, restore on exit
            runner = ResilientRunner(wf, "ckpts/run", preemption=guard)
            try:
                runner.run(state, n_steps=10_000)
            except Preempted:
                sys.exit(0)               # checkpoint is on disk; requeue

    or implicit — ``ResilientRunner(preemption=True)`` builds and installs
    a default guard for the duration of each :meth:`run`.

    Thread/signal semantics: the flag is a :class:`threading.Event`, so
    tripping is safe from signal handlers, provider-poll results, and
    other threads alike.  Handler installation must happen on the main
    thread (a CPython restriction); polling can happen anywhere.

    :param signals: signal numbers to intercept (default
        ``(SIGTERM, SIGINT)``).
    :param provider_hook: optional zero-argument callable polled by
        :attr:`triggered`; return a truthy value (a string becomes the
        recorded reason) when the platform announced maintenance /
        preemption.  A hook that *raises* is disabled after a warning —
        a broken poller must not veto every future segment boundary.
    """

    def __init__(
        self,
        *,
        signals: Iterable[Union[int, signal.Signals]] = (
            signal.SIGTERM,
            signal.SIGINT,
        ),
        provider_hook: Callable[[], object] | None = None,
    ):
        self.signals = tuple(signals)
        self.provider_hook = provider_hook
        self._event = threading.Event()
        self._reason: str | None = None
        self._prev: dict = {}
        self._installed = False

    # -- handler lifecycle -------------------------------------------------
    @property
    def installed(self) -> bool:
        """Whether this guard's handlers are currently installed."""
        return self._installed

    def install(self) -> "PreemptionGuard":
        """Install the signal handlers, remembering the previous ones.
        Idempotent; returns ``self``.  Main thread only (CPython)."""
        if self._installed:
            return self
        for sig in self.signals:
            self._prev[sig] = signal.signal(sig, self._handler)
        self._installed = True
        return self

    def uninstall(self) -> None:
        """Restore the signal handlers that were active before
        :meth:`install`.  Idempotent."""
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev if prev is not None else signal.SIG_DFL)
            except (ValueError, OSError, TypeError):  # pragma: no cover
                pass  # interpreter teardown / non-main thread
        self._prev.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def _handler(self, signum, frame) -> None:
        del frame
        if self._event.is_set():
            # Second strike: the graceful path already had its chance.
            # Give the signal its default (usually fatal) meaning back so
            # an operator hammering Ctrl-C, or a scheduler escalating, can
            # always kill a wedged shutdown.
            self.uninstall()
            signal.raise_signal(signum)
            return
        try:
            name = signal.Signals(signum).name
        except ValueError:  # pragma: no cover - exotic signal number
            name = str(signum)
        self.trip(f"signal {name}")

    # -- tripping ----------------------------------------------------------
    def trip(self, reason: str = "manual") -> None:
        """Set the flag (signal handler, provider callback, or test)."""
        if self._reason is None:
            self._reason = str(reason)
        self._event.set()

    def reset(self) -> None:
        """Clear the flag and reason (a new run through the same guard).

        ``ResilientRunner(preemption=True)`` resets its own guard at every
        ``run()``; a caller-owned guard (``preemption=guard``) must be
        reset by the caller before reusing it for another run — otherwise
        the stale flag trips the new run at its first boundary."""
        self._event.clear()
        self._reason = None

    @property
    def reason(self) -> str | None:
        """What tripped the guard, or ``None``."""
        return self._reason

    @property
    def triggered(self) -> bool:
        """Whether the run should stop at the next boundary.  Polls
        ``provider_hook`` (when set) in addition to the signal flag."""
        if self._event.is_set():
            return True
        if self.provider_hook is not None:
            try:
                notice = self.provider_hook()
            except Exception as e:  # noqa: BLE001 - see docstring
                warnings.warn(
                    f"preemption provider_hook raised {e!r}; disabling the "
                    f"hook (signals still guarded)"
                )
                self.provider_hook = None
                return False
            if notice:
                self.trip(
                    notice
                    if isinstance(notice, str)
                    else "provider maintenance event"
                )
                return True
        return False
