"""Run-health diagnostics for long evolutionary runs.

PR 1 (``resilience/runner.py``) made runs survive *infrastructure* faults —
backend loss, hangs, NaN fitness rows.  A multi-hour run can still silently
waste its budget on a *degenerate search*: non-finite values creeping into
the algorithm-state pytree (not just fitness), the population collapsing to
a point, an ES step size under/overflowing, or the best fitness flatlining
for thousands of generations.  None of those raise; all of them make every
further generation worthless.

:class:`HealthProbe` scans a workflow state **between** the supervisor's
jitted chunks and renders a structured :class:`HealthReport`:

* **non-finite state** — any NaN/±Inf in any floating leaf of the state
  pytree (algorithm, problem, and monitor sub-states alike; PRNG-key and
  integer leaves are skipped, and leaves whose path matches
  ``nonfinite_skip`` are exempt for algorithms that use ``inf`` as an
  in-band sentinel);
* **diversity collapse** — the largest per-dimension spread (std over the
  population axis) of ``state.algorithm.pop`` fell under
  ``diversity_floor``: the whole population sits in a vanishing box and
  recombination can no longer explore;
* **step-size out of range** — an ES ``sigma`` leaf left
  ``step_size_range`` (collapse to ~0 freezes the search; blow-up past the
  bound width turns it into rejection sampling);
* **stagnation** — the best fitness (monitor top-k when available, else
  ``min(state.algorithm.fit)``) improved less than ``stagnation_tol`` over
  the last ``stagnation_window`` probes.

The numeric scan is one jit-compiled program per state structure (compiled
once, then microseconds per probe — see ``tools/bench_health_overhead.py``
for the <5 % overhead budget); only the handful of scalar verdicts cross to
the host.  The stagnation window is host-side state: the
:class:`~evox_tpu.resilience.ResilientRunner` persists it in each
checkpoint's manifest so resumed runs replay probe decisions bit-identically
(see ``restart.py``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp

from ..utils.checkpoint import _path_str  # one format for leaf-path names

__all__ = ["HealthProbe", "HealthReport", "scan_state"]


def _is_prng(leaf: Any) -> bool:
    return isinstance(leaf, jax.Array) and jax.dtypes.issubdtype(
        leaf.dtype, jax.dtypes.prng_key
    )


def _subtree(state: Any, name: str) -> Any | None:
    """``state[name]`` when ``state`` is a mapping that has it, else None."""
    if isinstance(state, Mapping) and name in state:
        return state[name]
    return None


def scan_state(
    state: Any,
    *,
    check_nonfinite: bool = True,
    nonfinite_skip: Sequence[str] = (),
    diversity: bool = False,
    step_size: bool = False,
    shards: int | None = None,
) -> dict[str, Any]:
    """Pure ``state -> {metric: scalar}`` health scan — jittable; all
    branching is on the *structure* of ``state`` (static under jit).

    Shared by :class:`HealthProbe` (which thresholds the metrics into a
    verdict) and ``StdWorkflow.health_metrics`` (which surfaces them raw).
    Keys are emitted only when the state supports them, so the dict is
    stable per state structure:

    * ``nonfinite`` — per-leaf-path counts of NaN/±Inf scalars (floating
      leaves only; PRNG keys and ``nonfinite_skip`` matches excluded);
    * ``diversity`` — largest per-dimension std of ``algorithm.pop``;
    * ``step_size_min`` / ``step_size_max`` — extrema of ``algorithm.sigma``;
    * ``best_fitness`` — monitor top-k best (minimizing frame) when
      available, else ``min(algorithm.fit)``;
    * ``shard_nonfinite`` / ``shard_diversity`` — with ``shards=N`` on a
      distributed run, the non-finite count of ``algorithm.fit`` and the
      largest per-dimension population spread aggregated **per shard**
      (contiguous row blocks of the population axis, matching
      ``ShardedProblem``'s layout).  One corrupted shard then shows up as
      one hot row instead of diluting into whole-population averages —
      the signal behind the probe's dead-shard verdict.  Emitted only when
      the population axis divides ``N``.
    """
    out: dict[str, Any] = {}
    if check_nonfinite:
        counts = {}
        for key_path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
            name = _path_str(key_path)
            if any(skip in name for skip in nonfinite_skip):
                continue
            if _is_prng(leaf) or not (
                hasattr(leaf, "dtype")
                and jnp.issubdtype(leaf.dtype, jnp.floating)
            ):
                continue
            counts[name] = jnp.sum(~jnp.isfinite(leaf), dtype=jnp.int32)
        out["nonfinite"] = counts
    algo = _subtree(state, "algorithm")
    algo = algo if algo is not None else state
    pop = _subtree(algo, "pop")
    if (
        diversity
        and pop is not None
        and getattr(pop, "ndim", 0) == 2
        and jnp.issubdtype(pop.dtype, jnp.floating)
    ):
        # Largest per-dimension spread: below a floor means EVERY dimension
        # collapsed — the population sits in a vanishing box.
        out["diversity"] = jnp.max(jnp.std(pop, axis=0))
    fit = _subtree(algo, "fit")
    if (
        shards
        and shards > 1
        and fit is not None
        and getattr(fit, "ndim", 0) in (1, 2)
        and jnp.issubdtype(fit.dtype, jnp.floating)
    ):
        # Per-shard non-finite fitness rows: a whole row of NaN/Inf on one
        # shard (its count == its row budget) is the dead-shard signature.
        # Aggregation uses the parallel layer's row→shard mapping (segment
        # ops, not a reshape) so ragged populations — the
        # ShardedProblem(pad=True) case, where the last shard owns fewer
        # real rows — keep their shard metrics instead of silently losing
        # them.
        from ..parallel import shard_row_ids

        ids = shard_row_ids(fit.shape[0], shards)
        row_bad = ~jnp.isfinite(fit)
        if fit.ndim == 2:
            row_bad = jnp.any(row_bad, axis=-1)
        out["shard_nonfinite"] = jax.ops.segment_sum(
            row_bad.astype(jnp.int32), ids, num_segments=shards
        )
        out["shard_rows"] = jax.ops.segment_sum(
            jnp.ones_like(ids, dtype=jnp.int32), ids, num_segments=shards
        )
    if (
        diversity  # same gate as the whole-population spread: the verdict
        and shards  # needs a floor, so don't compute (and ship) unusable data
        and shards > 1
        and pop is not None
        and getattr(pop, "ndim", 0) == 2
        and jnp.issubdtype(pop.dtype, jnp.floating)
    ):
        from ..parallel import shard_row_ids

        ids = shard_row_ids(pop.shape[0], shards)
        n_s = jax.ops.segment_sum(
            jnp.ones((pop.shape[0],), pop.dtype), ids, num_segments=shards
        )
        denom = jnp.maximum(n_s, 1.0)[:, None]
        mean = jax.ops.segment_sum(pop, ids, num_segments=shards) / denom
        # Centered (two-pass) variance: the E[x²]-E[x]² shortcut cancels
        # catastrophically in float32 exactly when the spread is tiny —
        # the regime the collapse floor exists to detect.
        centered = pop - mean[ids]
        var = jax.ops.segment_sum(centered**2, ids, num_segments=shards) / denom
        spread = jnp.sqrt(var).max(axis=-1)
        # A shard owning zero rows (ragged tail) has no spread to collapse:
        # report +inf so the floor never fires on it.
        out["shard_diversity"] = jnp.where(n_s > 0, spread, jnp.inf)
    sigma = _subtree(algo, "sigma")
    if (
        step_size
        and sigma is not None
        and hasattr(sigma, "dtype")
        and jnp.issubdtype(sigma.dtype, jnp.floating)
    ):
        out["step_size_min"] = jnp.min(sigma)
        out["step_size_max"] = jnp.max(sigma)
    best = _best_fitness_expr(state, algo)
    if best is not None:
        out["best_fitness"] = best
    return out


def _best_fitness_expr(state: Any, algo: Any):
    """Best fitness in the minimizing frame: the monitor's running top-k
    when present (monotone best-so-far), else this generation's
    ``min(fit)``.  ``None`` when the state exposes neither (e.g.
    multi-objective states, which have no scalar best)."""
    mon = _subtree(state, "monitor")
    if mon is not None:
        topk = _subtree(mon, "topk_fitness")
        if (
            topk is not None
            and getattr(topk, "ndim", 0) == 1
            and topk.size > 0
            and jnp.issubdtype(topk.dtype, jnp.floating)
        ):
            return topk[0]
    fit = _subtree(algo, "fit")
    if (
        fit is not None
        and getattr(fit, "ndim", 0) == 1
        and fit.size > 0
        and jnp.issubdtype(fit.dtype, jnp.floating)
    ):
        return jnp.min(fit)
    return None


@dataclass
class HealthReport:
    """Structured verdict of one :meth:`HealthProbe.check` call.

    ``healthy`` is the conjunction of the individual detectors; ``reasons``
    carries one human-readable line per tripped detector (empty when
    healthy).  Metric fields are ``None`` when the corresponding detector
    did not apply to this state (no ``pop`` leaf, no ``sigma`` leaf, window
    not yet full, ...)."""

    generation: int
    healthy: bool
    reasons: list[str] = field(default_factory=list)
    nonfinite_leaves: dict[str, int] = field(default_factory=dict)
    diversity: float | None = None
    diversity_collapse: bool = False
    step_size_min: float | None = None
    step_size_max: float | None = None
    step_size_out_of_range: bool = False
    best_fitness: float | None = None
    stagnation_improvement: float | None = None
    stagnating: bool = False
    # Per-shard aggregation (``HealthProbe(shards=N)`` on distributed runs;
    # ``None`` when the probe is shard-blind or the state has no population
    # axis that divides N).
    shard_nonfinite: list[int] | None = None
    dead_shards: list[int] = field(default_factory=list)
    shard_diversity: list[float] | None = None
    collapsed_shards: list[int] = field(default_factory=list)
    # True when the unhealthy verdict came from the control plane's
    # flight-window trend analysis (``evox_tpu.control``) rather than the
    # probe's instantaneous threshold detectors — see :meth:`with_trend`.
    trend: bool = False

    def with_trend(self, reasons: Sequence[str]) -> "HealthReport":
        """A copy of this report rendered unhealthy by a controller
        trend verdict: ``healthy=False``, ``trend=True``, the trend
        reasons appended after any probe reasons.  The probe's metric
        fields are untouched — the trend verdict is *about* the window's
        trajectory, which the flight recorder (and the journaled
        decision's evidence) documents."""
        import dataclasses

        return dataclasses.replace(
            self,
            healthy=False,
            trend=True,
            reasons=[*self.reasons, *reasons],
        )


class HealthProbe:
    """Between-chunk state scanner producing :class:`HealthReport` verdicts.

    Usage (standalone)::

        probe = HealthProbe(diversity_floor=1e-6, stagnation_window=5)
        report = probe.check(state, generation=120)
        if not report.healthy:
            print(report.reasons)

    Usage (supervised — the intended path)::

        runner = ResilientRunner(
            wf, "ckpts/run",
            health=HealthProbe(stagnation_window=5, stagnation_tol=1e-9),
            restart=RollbackToCheckpoint(),
        )

    The probe is cheap but not free: the scan is jitted once per state
    structure and each ``check`` costs one device->host sync of a few
    scalars.  Determinism: ``check`` is a pure function of ``(state, the
    probe's stagnation window)``; the runner checkpoints the window, so a
    resumed run reaches identical verdicts.
    """

    def __init__(
        self,
        *,
        check_nonfinite: bool = True,
        nonfinite_skip: Sequence[str] = (),
        diversity_floor: float | None = None,
        step_size_range: tuple[float, float] | None = (1e-12, 1e6),
        stagnation_window: int = 0,
        stagnation_tol: float = 0.0,
        shards: int | None = None,
    ):
        """
        :param check_nonfinite: scan every floating leaf of the state pytree
            for NaN/±Inf (PRNG-key and integer/bool leaves are skipped).
        :param nonfinite_skip: path substrings (e.g. ``"archive_fit"``)
            whose leaves are exempt from the non-finite scan — for
            algorithms that legitimately keep ``inf`` sentinels in state.
        :param diversity_floor: flag diversity collapse when the *largest*
            per-dimension std of ``state.algorithm.pop`` drops below this;
            ``None`` disables the detector.
        :param shards: shard count of the distributed run this probe watches
            (``mesh.shape["pop"]``).  Adds per-shard aggregation: non-finite
            fitness counts and population diversity per contiguous row block
            (``ShardedProblem``'s layout), a **dead-shard** verdict when an
            entire shard's fitness is non-finite, and — with
            ``diversity_floor`` set — a **collapsed-shard** verdict when one
            shard's spread falls under the floor while the whole-population
            spread still looks healthy.  Note the quarantine interplay: with
            ``StdWorkflow(quarantine_nonfinite=True)`` (the default) the
            penalty substitution happens *before* the fitness reaches the
            algorithm state, so dead shards are detected there (shard-granular
            quarantine + ``EvalMonitor.num_shard_quarantines``) rather than
            by this probe; the probe's dead-shard verdict covers quarantine-off
            runs and custom workflows.  ``None`` (default) disables.
        :param step_size_range: ``(lo, hi)`` bounds on the ``sigma`` leaf of
            the algorithm state (checked against ``min(sigma)``/``max(sigma)``
            for per-dimension step sizes); ``None`` disables.
        :param stagnation_window: flag stagnation when the best fitness
            improved by less than ``stagnation_tol`` over this many
            consecutive probes; ``0`` disables, and ``>= 2`` is required
            otherwise (a window of 1 compares a value against itself).
            With a runner this counts chunk boundaries, i.e.
            ``stagnation_window * checkpoint_every`` generations.
        :param stagnation_tol: minimum improvement (in the minimizing
            fitness frame) the window must show to count as progress.
        """
        if stagnation_window < 0 or stagnation_window == 1:
            # A window of 1 compares a value against itself: improvement is
            # identically 0 and every probe reads as stagnant.
            raise ValueError(
                f"stagnation_window must be 0 (disabled) or >= 2 (a window "
                f"of 1 cannot measure improvement), got {stagnation_window}"
            )
        if step_size_range is not None and not (
            step_size_range[0] <= step_size_range[1]
        ):
            raise ValueError(
                f"step_size_range must be (lo, hi) with lo <= hi, got "
                f"{step_size_range}"
            )
        if shards is not None and shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        self.check_nonfinite = check_nonfinite
        self.nonfinite_skip = tuple(nonfinite_skip)
        self.diversity_floor = diversity_floor
        self.step_size_range = step_size_range
        self.stagnation_window = int(stagnation_window)
        self.stagnation_tol = float(stagnation_tol)
        self.shards = None if shards is None else int(shards)
        self._window: list[float] = []
        # Per-lane stagnation windows for multi-tenant packs, keyed by the
        # caller's stable lane id (the service layer keys on tenant uid, so
        # a tenant's window follows it across lane moves and
        # eviction/readmission).  Disjoint from the solo window: one probe
        # instance may watch one pack.
        self._lane_windows: dict[int, list[float]] = {}
        # One compiled scan per state structure (jit re-traces on structure
        # change, e.g. after an IPOP-style population regrow).
        self._scan = jax.jit(self._scan_impl)
        # Lane-axis variant for tenant packs: one vmapped scan over the
        # leading lane axis, thresholded per lane on the host.
        self._lane_scan = jax.jit(jax.vmap(self._scan_impl))

    # -- host-side window (persisted via checkpoint manifests) --------------
    @property
    def window(self) -> tuple[float, ...]:
        """Best-fitness values of the most recent probes (newest last)."""
        return tuple(self._window)

    def reset(self) -> None:
        """Clear the stagnation window (a fresh run's probe history)."""
        self._window = []

    def restore(self, window: Sequence[float]) -> None:
        """Restore the stagnation window from a checkpoint manifest so a
        resumed run replays probe decisions identically."""
        self._window = [float(x) for x in window]
        if self.stagnation_window:
            del self._window[: -self.stagnation_window]

    # -- per-lane windows (multi-tenant packs) ------------------------------
    def lane_window(self, lane_id: int) -> tuple[float, ...]:
        """Best-fitness window of one pack lane (see :meth:`check_lanes`);
        empty for an unknown lane.  The service layer persists this in the
        tenant's checkpoint manifest, exactly like the runner persists
        :attr:`window`."""
        return tuple(self._lane_windows.get(int(lane_id), ()))

    def restore_lane(self, lane_id: int, window: Sequence[float]) -> None:
        """Restore one lane's stagnation window (tenant readmission), so
        the readmitted tenant replays probe decisions identically."""
        win = [float(x) for x in window]
        if self.stagnation_window:
            del win[: -self.stagnation_window]
        self._lane_windows[int(lane_id)] = win

    def reset_lane(self, lane_id: int) -> None:
        """Clear one lane's window (fresh tenant / post-restart grace —
        the per-lane analogue of :meth:`reset`)."""
        self._lane_windows.pop(int(lane_id), None)

    # -- the jitted scan -----------------------------------------------------
    def _scan_impl(self, state: Any) -> dict[str, Any]:
        return scan_state(
            state,
            check_nonfinite=self.check_nonfinite,
            nonfinite_skip=self.nonfinite_skip,
            diversity=self.diversity_floor is not None,
            step_size=self.step_size_range is not None,
            shards=self.shards,
        )

    # -- the host-side verdict ----------------------------------------------
    def check(self, state: Any, generation: int = 0) -> HealthReport:
        """Scan ``state`` and return a :class:`HealthReport`.

        Appends to the stagnation window as a side effect — call exactly
        once per chunk boundary (the runner does)."""
        raw = jax.device_get(self._scan(state))
        return self._verdict(raw, generation, self._window)

    def check_lanes(
        self,
        states: Any,
        generation: int = 0,
        lane_ids: Sequence[int] | None = None,
    ) -> list[HealthReport]:
        """Per-lane verdicts for a tenant pack: ``states`` carries a
        leading lane axis (the stacked per-tenant states a
        ``TenantPack`` steps through one vmapped segment), and each lane
        is thresholded independently — one :class:`HealthReport` per
        requested lane, in ``lane_ids`` order.

        ``lane_ids`` maps the rows to *stable* identities (the service
        passes tenant uids) so each lane's stagnation window follows its
        tenant across lane moves and eviction/readmission; ``None`` uses
        the row indices.  One device scan serves every lane (the scan is
        vmapped over the lane axis); appends to each requested lane's
        window as a side effect — call exactly once per segment boundary
        per lane, and skip unoccupied lanes by omitting their rows from
        ``lane_ids``... which is why ``lane_ids`` may be a sparse
        ``[(row, id), ...]`` mapping too."""
        raw = jax.device_get(self._lane_scan(states))
        if lane_ids is None:
            n = jax.tree_util.tree_leaves(states)[0].shape[0]
            pairs = [(row, row) for row in range(n)]
        elif lane_ids and isinstance(lane_ids[0], tuple):
            pairs = [(int(r), int(i)) for r, i in lane_ids]
        else:
            pairs = list(enumerate(int(i) for i in lane_ids))
        reports = []
        for row, lane_id in pairs:
            lane_raw = jax.tree_util.tree_map(lambda x: x[row], raw)
            window = self._lane_windows.setdefault(lane_id, [])
            reports.append(self._verdict(lane_raw, generation, window))
        return reports

    def _verdict(
        self, raw: Mapping[str, Any], generation: int, window: list[float]
    ) -> HealthReport:
        """Threshold one (host-side) metric dict into a report, advancing
        the given stagnation window in place."""
        reasons: list[str] = []

        nonfinite = {
            name: int(n)
            for name, n in raw.get("nonfinite", {}).items()
            if int(n) > 0
        }
        if nonfinite:
            listed = ", ".join(f"{k} ({v})" for k, v in sorted(nonfinite.items()))
            reasons.append(f"non-finite values in state leaves: {listed}")

        diversity = raw.get("diversity")
        diversity = None if diversity is None else float(diversity)
        diversity_collapse = (
            self.diversity_floor is not None
            and diversity is not None
            and diversity < self.diversity_floor
        )
        if diversity_collapse:
            reasons.append(
                f"population diversity collapsed: max per-dimension spread "
                f"{diversity:.3e} < floor {self.diversity_floor:.3e}"
            )

        shard_nonfinite = raw.get("shard_nonfinite")
        dead_shards: list[int] = []
        if shard_nonfinite is not None:
            shard_nonfinite = [int(n) for n in shard_nonfinite]
            shard_rows = [int(r) for r in raw["shard_rows"]]
            # A shard is dead when EVERY row it owns is non-finite; shards
            # owning zero rows (ragged tails) have nothing to be dead about.
            dead_shards = [
                s
                for s, (n, rows) in enumerate(zip(shard_nonfinite, shard_rows))
                if rows > 0 and n == rows
            ]
            if dead_shards:
                reasons.append(
                    f"dead shard(s) {dead_shards}: every fitness row of the "
                    f"shard is non-finite"
                )
        shard_diversity = raw.get("shard_diversity")
        collapsed_shards: list[int] = []
        if shard_diversity is not None:
            shard_diversity = [float(d) for d in shard_diversity]
            if self.diversity_floor is not None:
                collapsed_shards = [
                    s
                    for s, d in enumerate(shard_diversity)
                    if d < self.diversity_floor
                ]
            if collapsed_shards:
                reasons.append(
                    f"collapsed shard(s) {collapsed_shards}: per-shard "
                    f"population spread under the "
                    f"{self.diversity_floor:.3e} floor"
                )

        ss_min = raw.get("step_size_min")
        ss_min = None if ss_min is None else float(ss_min)
        ss_max = raw.get("step_size_max")
        ss_max = None if ss_max is None else float(ss_max)
        step_size_out_of_range = False
        if self.step_size_range is not None and ss_min is not None:
            lo, hi = self.step_size_range
            # A NaN sigma is out of range too (comparisons are False, so
            # test the healthy band and negate).
            inside = (ss_min >= lo) and (ss_max <= hi)
            step_size_out_of_range = not inside
            if step_size_out_of_range:
                reasons.append(
                    f"step size out of range: sigma in [{ss_min:.3e}, "
                    f"{ss_max:.3e}], allowed [{lo:.3e}, {hi:.3e}]"
                )

        best = raw.get("best_fitness")
        best = None if best is None else float(best)
        stagnating = False
        improvement = None
        if self.stagnation_window > 0 and best is not None:
            window.append(best)
            del window[: -self.stagnation_window]
            if len(window) == self.stagnation_window:
                improvement = window[0] - window[-1]
                # NaN improvement compares False -> not flagged here; the
                # non-finite detector owns that failure mode.
                stagnating = improvement <= self.stagnation_tol
                if stagnating:
                    reasons.append(
                        f"best fitness stagnating: improvement "
                        f"{improvement:.3e} <= tol {self.stagnation_tol:.3e} "
                        f"over the last {self.stagnation_window} probes"
                    )

        return HealthReport(
            generation=int(generation),
            healthy=not reasons,
            reasons=reasons,
            nonfinite_leaves=nonfinite,
            diversity=diversity,
            diversity_collapse=diversity_collapse,
            step_size_min=ss_min,
            step_size_max=ss_max,
            step_size_out_of_range=step_size_out_of_range,
            best_fitness=best,
            stagnation_improvement=improvement,
            stagnating=stagnating,
            shard_nonfinite=shard_nonfinite,
            dead_shards=dead_shards,
            shard_diversity=shard_diversity,
            collapsed_shards=collapsed_shards,
        )
