"""Chaos conductor: deterministic whole-stack fault orchestration.

Every fault plane this repo grew — process SIGKILL to members and the
router (:mod:`tests` model it as *abandonment*: drop the object with no
shutdown path, rebuild over the same root), :class:`.FaultyStore` disk
faults by save index, :class:`.FaultyTransport` wire faults by request
index, :class:`.FaultyProblem` lane faults by tenant, straggler and
partition windows — composes here into ONE seeded, JSON-serializable
timeline:

* :class:`ChaosPlan` — the scenario DSL.  A plan is plain data
  (``to_json`` / ``from_json`` round-trips; :meth:`ChaosPlan.digest` is
  the SHA-256 of its canonical JSON), audited at construction time by
  the same :func:`.validate_schedule` discipline every injector uses
  (negative rounds, out-of-range members, a member scheduled to be both
  SIGKILLed and partitioned in the same round — contradictory fates —
  all fail loudly before anything runs).  :meth:`ChaosPlan.from_seed`
  derives a whole scenario from one integer, deterministically.
* :class:`ChaosConductor` — runs a routed multi-member fleet through
  the plan round by round, journals every injected event into a
  canonical ``chaos_events.jsonl`` (no wall-clock inside the records:
  the same ``(seed, plan digest)`` reproduces the file **bit for
  bit**), and between rounds audits the
  :data:`~evox_tpu.resilience.invariants.INVARIANTS` registry against a
  :func:`build_audit_context` snapshot of the live fleet.  Each
  violation is dumped as a structured postmortem evidence bundle
  through the :class:`~evox_tpu.obs.FlightRecorder` path.
* :class:`ChaosReport` — the run's JSON-ready verdict: rounds, acks,
  completions, the injected-event journal digest, every violation, and
  the per-member SLO burn-rate report (``tools/soak.py`` turns the same
  report into the scale-ladder artifact).

The conductor is a *test harness with a statusz face*: attach it and
the router/daemon ``/statusz`` grows a ``chaos`` section
(:meth:`ChaosConductor.statusz_payload`), and ``evoxtop`` renders the
soak strip from it.
"""

from __future__ import annotations

import hashlib
import json
import random as _random
import time
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Any, Callable, Mapping

from ..obs import FlightRecorder, default_slos
from ..service import (
    AdmissionError,
    RequestJournal,
    ServiceMember,
    TenantRouter,
    TenantSpec,
    TenantStatus,
)
from ..utils import ExecutableCache
from ..utils.checkpoint import atomic_write_text
from .faults import FaultyProblem, FaultyStore
from .schedule import validate_schedule
from .invariants import (
    AuditContext,
    InvariantViolation,
    audit_invariants,
)
from .transport import FaultyTransport

__all__ = [
    "ChaosPlan",
    "ChaosConductor",
    "ChaosReport",
    "build_audit_context",
]

#: Plan ops and the fields each requires beyond ``round`` / ``op``.
_EVENT_FIELDS: dict[str, set[str]] = {
    "kill-member": {"member"},
    "kill-router": set(),
    "partition-member": {"member", "until"},
    "straggle-member": {"member", "until", "delay_seconds"},
}

_CANONICAL = {"sort_keys": True, "separators": (",", ":")}


def _canonical_json(payload: Any) -> str:
    return json.dumps(payload, **_CANONICAL)


@dataclass
class ChaosPlan:
    """One deterministic whole-stack fault scenario, as plain data.

    :param name: scenario label (rides the report and the statusz strip).
    :param seed: the scenario's identity; :meth:`from_seed` derives every
        schedule from it, and the conductor stamps it into the report so
        any failure reproduces from ``(seed, digest())`` alone.
    :param rounds: scheduling rounds the conductor drives (the drain
        phase afterwards runs fault-free until every tenant completes).
    :param members: fleet size (≥ 1).
    :param tenants: tenants submitted over the run.
    :param submit_rounds: per-tenant submission round, ``len == tenants``,
        each in ``[0, rounds)``.
    :param events: process/link timeline ops —
        ``{"round", "op": "kill-member", "member"}``,
        ``{"round", "op": "kill-router"}``,
        ``{"round", "op": "partition-member", "member", "until"}``
        (the member's link drops everything for rounds ``[round,
        until)``), ``{"round", "op": "straggle-member", "member",
        "until", "delay_seconds"}``.
    :param store_faults: :class:`.FaultyStore` kwargs per disk scope —
        key ``"router"`` (the router journal's store) or
        ``"member:<i>"`` (that member's whole store: journal appends
        and checkpoint publishes share the save-index schedule).
    :param wire_faults: :class:`.FaultyTransport` kwargs per member
        link, keyed by member index as a string (JSON keys are strings).
        A rebuilt link (member or router kill) restarts the request
        index at 0 and re-fires the schedule — deterministically.
    :param lane_faults: :class:`.FaultyProblem` per-lane fault spec per
        tenant index (string key); applied to that tenant's problem at
        submission, keyed by its pinned uid.
    :param n_steps: generation budget per tenant.
    :param lanes_per_pack: member pack width.
    :param segment_steps: member segment cadence.
    """

    name: str
    seed: int
    rounds: int
    members: int
    tenants: int
    submit_rounds: list[int] = field(default_factory=list)
    events: list[dict[str, Any]] = field(default_factory=list)
    store_faults: dict[str, dict[str, Any]] = field(default_factory=dict)
    wire_faults: dict[str, dict[str, Any]] = field(default_factory=dict)
    lane_faults: dict[str, dict[str, Any]] = field(default_factory=dict)
    n_steps: int = 8
    lanes_per_pack: int = 4
    segment_steps: int = 4

    def __post_init__(self) -> None:
        self.validate()

    # -- construction-time audit --------------------------------------------
    def validate(self) -> None:
        """The :func:`.validate_schedule` discipline, one level up: every
        contradiction a plan can encode fails here, never mid-run."""
        for name, value, floor in (
            ("members", self.members, 1),
            ("rounds", self.rounds, 1),
            ("tenants", self.tenants, 0),
            ("n_steps", self.n_steps, 1),
            ("lanes_per_pack", self.lanes_per_pack, 1),
            ("segment_steps", self.segment_steps, 1),
        ):
            if int(value) < floor:
                raise ValueError(
                    f"ChaosPlan.{name} must be >= {floor}, got {value}"
                )
        if len(self.submit_rounds) != self.tenants:
            raise ValueError(
                f"ChaosPlan.submit_rounds must schedule every tenant "
                f"exactly once ({self.tenants} tenants, "
                f"{len(self.submit_rounds)} rounds given)"
            )
        for t, r in enumerate(self.submit_rounds):
            if not (0 <= int(r) < self.rounds):
                raise ValueError(
                    f"ChaosPlan.submit_rounds[{t}] = {r} is outside "
                    f"[0, {self.rounds})"
                )
        kills: dict[int, set[int]] = {}
        partitions: dict[int, set[int]] = {}
        straggles: dict[int, set[int]] = {}
        for n, ev in enumerate(self.events):
            op = ev.get("op")
            if op not in _EVENT_FIELDS:
                raise ValueError(
                    f"ChaosPlan.events[{n}] has unknown op {op!r}; valid "
                    f"ops are {sorted(_EVENT_FIELDS)}"
                )
            required = {"round", "op"} | _EVENT_FIELDS[op]
            validate_schedule(
                f"ChaosPlan.events[{n}] ({op})",
                fields=ev,
                known=required,
            )
            missing = sorted(required - set(ev))
            if missing:
                raise ValueError(
                    f"ChaosPlan.events[{n}] ({op}) is missing field(s) "
                    f"{missing}"
                )
            r = int(ev["round"])
            if not (0 <= r < self.rounds):
                raise ValueError(
                    f"ChaosPlan.events[{n}] ({op}) fires at round {r}, "
                    f"outside [0, {self.rounds})"
                )
            if op == "kill-router":
                continue
            m = int(ev["member"])
            if not (0 <= m < self.members):
                raise ValueError(
                    f"ChaosPlan.events[{n}] ({op}) targets member {m}, "
                    f"outside [0, {self.members})"
                )
            if op == "kill-member":
                kills.setdefault(m, set()).add(r)
                continue
            until = int(ev["until"])
            if not (r < until <= self.rounds):
                raise ValueError(
                    f"ChaosPlan.events[{n}] ({op}) window [{r}, {until}) "
                    f"is empty or runs past round {self.rounds}"
                )
            window = set(range(r, until))
            if op == "partition-member":
                partitions.setdefault(m, set()).update(window)
            else:
                if float(ev["delay_seconds"]) < 0:
                    raise ValueError(
                        f"ChaosPlan.events[{n}] (straggle-member) "
                        f"delay_seconds must be >= 0, got "
                        f"{ev['delay_seconds']}"
                    )
                straggles.setdefault(m, set()).update(window)
        # Contradictory fates per member, the injector exclusivity rule
        # one level up: a SIGKILL cannot land over a partitioned link
        # (nothing reaches the process), and a link cannot both drop
        # everything and deliver late.
        for m in sorted(set(kills) | set(partitions) | set(straggles)):
            validate_schedule(
                f"ChaosPlan member {m}",
                indices={
                    "kill-member": sorted(kills.get(m, ())),
                    "partition-member": sorted(partitions.get(m, ())),
                    "straggle-member": sorted(straggles.get(m, ())),
                },
                exclusive=[
                    ("kill-member", "partition-member"),
                    ("partition-member", "straggle-member"),
                ],
            )
        for scope, kwargs in sorted(self.store_faults.items()):
            if scope != "router":
                prefix, _, index = scope.partition(":")
                if prefix != "member" or not index.isdigit() or not (
                    0 <= int(index) < self.members
                ):
                    raise ValueError(
                        f"ChaosPlan.store_faults scope {scope!r} is not "
                        f"'router' or 'member:<i>' with i in "
                        f"[0, {self.members})"
                    )
            FaultyStore(**kwargs)  # construction IS the audit
        for key, kwargs in sorted(self.wire_faults.items()):
            if not str(key).isdigit() or not (0 <= int(key) < self.members):
                raise ValueError(
                    f"ChaosPlan.wire_faults key {key!r} is not a member "
                    f"index in [0, {self.members})"
                )
            FaultyTransport(None, **kwargs)
        for key, spec in sorted(self.lane_faults.items()):
            if not str(key).isdigit() or not (0 <= int(key) < self.tenants):
                raise ValueError(
                    f"ChaosPlan.lane_faults key {key!r} is not a tenant "
                    f"index in [0, {self.tenants})"
                )
            validate_schedule(
                f"ChaosPlan.lane_faults[{key}]",
                fields=spec,
                known=set(FaultyProblem._LANE_FAULT_FIELDS),
            )

    # -- serialization -------------------------------------------------------
    def to_json(self) -> dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_json(cls, payload: Mapping[str, Any]) -> "ChaosPlan":
        return cls(**dict(payload))

    def digest(self) -> str:
        """SHA-256 of the canonical (sorted-key, compact) plan JSON: the
        scenario's reproducibility handle."""
        return hashlib.sha256(
            _canonical_json(self.to_json()).encode("utf-8")
        ).hexdigest()

    # -- derivation ----------------------------------------------------------
    @classmethod
    def from_seed(
        cls,
        seed: int,
        *,
        name: str | None = None,
        members: int = 3,
        tenants: int = 9,
        rounds: int = 8,
        kills: int = 2,
        wire: int = 2,
        disk: int = 2,
        lanes: int = 1,
        partitions: int = 1,
        n_steps: int = 8,
        lanes_per_pack: int = 4,
        segment_steps: int = 4,
    ) -> "ChaosPlan":
        """Derive a whole valid scenario from one integer.

        The generated mix leans on the *self-healing* fault flavors
        (ENOSPC/EIO heal on retry, dropped/torn/duplicated wire requests
        resolve through the journaled-placement retry path) so a seeded
        plan always leaves the fleet able to finish; the harsher flavors
        (torn journal appends, NaN lanes) stay available to hand-written
        plans."""
        rng = _random.Random(int(seed))
        submit_horizon = max(1, rounds // 2)
        submit_rounds = [rng.randrange(submit_horizon) for _ in range(tenants)]
        events: list[dict[str, Any]] = []
        killed_members: set[int] = set()
        kill_rounds = sorted(
            rng.sample(range(1, rounds), min(kills, rounds - 1))
        )
        for r in kill_rounds:
            target = rng.randrange(members + 1)
            if target == members:
                events.append({"round": r, "op": "kill-router"})
            else:
                events.append(
                    {"round": r, "op": "kill-member", "member": target}
                )
                killed_members.add(target)
        untouched = [m for m in range(members) if m not in killed_members]
        for _ in range(partitions):
            if not untouched or rounds < 3:
                break
            m = untouched.pop(rng.randrange(len(untouched)))
            start = rng.randrange(1, rounds - 1)
            until = min(rounds - 1, start + 1 + rng.randrange(2))
            if until <= start:
                until = start + 1
            events.append(
                {
                    "round": start,
                    "op": "partition-member",
                    "member": m,
                    "until": until,
                }
            )
        wire_faults: dict[str, dict[str, Any]] = {}
        for m in rng.sample(range(members), min(wire, members)):
            flavor = rng.choice(
                ("drop_replies", "duplicate_requests", "torn_replies",
                 "drop_requests")
            )
            wire_faults[str(m)] = {flavor: [rng.randrange(3)]}
        store_faults: dict[str, dict[str, Any]] = {}
        scopes = ["router"] + [f"member:{i}" for i in range(members)]
        for scope in rng.sample(scopes, min(disk, len(scopes))):
            flavor = rng.choice(("enospc_saves", "eio_saves"))
            # Low save indices land on journal appends (the first saves a
            # fresh store sees), the retry path the planes harden.
            store_faults[scope] = {flavor: [rng.randrange(2)]}
        lane_faults: dict[str, dict[str, Any]] = {}
        if tenants:
            for t in rng.sample(range(tenants), min(lanes, tenants)):
                lane_faults[str(t)] = {
                    "plateau_from": 1,
                    "plateau_until": 3,
                    "plateau_floor": 1.0,
                }
        return cls(
            name=name or f"seeded-{int(seed)}",
            seed=int(seed),
            rounds=rounds,
            members=members,
            tenants=tenants,
            submit_rounds=submit_rounds,
            events=events,
            store_faults=store_faults,
            wire_faults=wire_faults,
            lane_faults=lane_faults,
            n_steps=n_steps,
            lanes_per_pack=lanes_per_pack,
            segment_steps=segment_steps,
        )


@dataclass
class ChaosReport:
    """One chaos run's JSON-ready verdict."""

    plan_name: str
    plan_digest: str
    seed: int
    rounds_run: int
    tenants: int
    completed: int
    acks: int
    pending: int
    injected_events: int
    violations: list[dict[str, Any]]
    event_log: str
    event_log_sha256: str
    slo_burn_report: dict[str, Any]
    counters: dict[str, float]
    elapsed_seconds: float

    def to_json(self) -> dict[str, Any]:
        return asdict(self)


# -- fleet snapshot → audit context ------------------------------------------


def _read_journal(path: Any) -> tuple[list[dict[str, Any]], bool]:
    """Parse a request journal file read-only into plain ``{"kind",
    "data"}`` records, never mutating it (the owning plane's ``replay``
    handles quarantine); unparseable lines (a torn tail) are skipped.
    Returns ``(records, compacted)`` — compacted when a
    ``snapshot-anchor`` record is present."""
    records: list[dict[str, Any]] = []
    compacted = False
    p = Path(path)
    try:
        raw = p.read_bytes()
    except OSError:
        return records, compacted
    for line in raw.splitlines():
        if not line.strip():
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        body = obj.get("body") or {}
        kind = body.get("kind")
        if kind == "snapshot-anchor":
            compacted = True
            continue
        records.append({"kind": kind, "data": dict(body.get("data") or {})})
    return records, compacted


def build_audit_context(
    router: TenantRouter,
    *,
    acks: Any = (),
    round: int = 0,
    forgotten: Any = (),
    counters: Mapping[str, float] | None = None,
    previous_counters: Mapping[str, float] | None = None,
) -> AuditContext:
    """Snapshot a live routed fleet into the plain
    :class:`~evox_tpu.resilience.invariants.AuditContext` the invariant
    registry audits — journals parsed read-only from disk, placements
    and residency from the live objects.  Used by the conductor between
    rounds, by ``tools/soak.py`` between churn waves, and directly by
    tests."""
    router_records, router_compacted = _read_journal(router.journal.path)
    compacted_scopes: set[str] = {"router"} if router_compacted else set()
    member_records: dict[int, list[dict[str, Any]]] = {}
    resident: dict[int, set[str]] = {}
    completed: set[str] = set()
    slo_reports: dict[str, list[dict[str, Any]]] = {}
    records_since: dict[str, int] = {}
    compact_records: dict[str, int | None] = {}
    live_members = {i for i in router.members if router._usable(i)}
    for i, member in sorted(router.members.items()):
        scope = f"member:{i}"
        recs, compacted = _read_journal(member.daemon.journal.path)
        member_records[i] = recs
        if compacted:
            compacted_scopes.add(scope)
        tenants_dir = Path(member.root) / "tenants"
        if tenants_dir.is_dir():
            resident[i] = {p.name for p in tenants_dir.iterdir() if p.is_dir()}
        else:
            resident[i] = set()
        for tid, record in member.daemon.service._tenants.items():
            if record.status is TenantStatus.COMPLETED:
                completed.add(str(tid))
        if member.daemon.slo is not None:
            slo_reports[scope] = member.daemon.slo.describe()
        records_since[scope] = int(
            getattr(member.daemon.journal, "records_since_snapshot", 0) or 0
        )
        compact_records[scope] = member.daemon.compact_records
    records_since["router"] = int(
        getattr(router.journal, "records_since_snapshot", 0) or 0
    )
    compact_records["router"] = router.compact_records
    placements = {
        str(tid): {"member": int(p["member"]), "uid": int(p["uid"])}
        for tid, p in router._placements.items()
    }
    base_counters: dict[str, float] = {
        "router.uid_next": float(router._uid_next),
    }
    # Journal record counts are monotone by append-only-ness — except
    # across a compaction, which folds them by design.
    if "router" not in compacted_scopes:
        base_counters["router.journal_records"] = float(len(router_records))
    for i, recs in member_records.items():
        if f"member:{i}" not in compacted_scopes:
            base_counters[f"member:{i}.journal_records"] = float(len(recs))
    if counters:
        base_counters.update({str(k): float(v) for k, v in counters.items()})
    return AuditContext(
        round=int(round),
        acks=list(acks),
        router_records=router_records,
        member_records=member_records,
        placements=placements,
        completed=completed,
        forgotten=set(forgotten),
        live_members=live_members,
        resident=resident,
        counters=base_counters,
        previous_counters=dict(previous_counters or {}),
        slo_reports=slo_reports,
        records_since_snapshot=records_since,
        compact_records=compact_records,
        compacted_scopes=compacted_scopes,
    )


# -- link wrappers -----------------------------------------------------------


class _PartitionedLink:
    """A member link inside a partition window: nothing is delivered,
    nothing comes back (the router's ``member-link`` refusal path)."""

    def __init__(self, member_index: int):
        self.member_index = int(member_index)
        self.events: list[tuple[int, str]] = []
        self._n = 0

    def request(
        self, method: str, path: str, headers: dict, body: bytes
    ) -> tuple[int, dict, bytes]:
        index = self._n
        self._n += 1
        self.events.append((index, "partition-drop"))
        raise ConnectionError(
            f"injected: member {self.member_index} link partitioned"
        )


class _StragglerLink:
    """A member link inside a straggle window: everything is delivered,
    late."""

    def __init__(self, inner: Any, seconds: float):
        self.inner = inner
        self.seconds = float(seconds)
        self.events: list[tuple[int, str]] = []
        self._n = 0

    def request(
        self, method: str, path: str, headers: dict, body: bytes
    ) -> tuple[int, dict, bytes]:
        index = self._n
        self._n += 1
        self.events.append((index, "straggle"))
        time.sleep(self.seconds)
        return self.inner.request(method, path, headers, body)


def _silent(fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return fn(*args, **kwargs)


# -- the conductor -----------------------------------------------------------


class ChaosConductor:
    """Drive a routed multi-member fleet through one :class:`ChaosPlan`.

    :param root: run directory (members at ``m<i>/``, router at
        ``router/``, the canonical injected-event journal at
        ``chaos_events.jsonl``, the report at ``chaos_report.json``,
        postmortem bundles under ``postmortems/``).
    :param plan: the scenario.
    :param spec_factory: optional ``(tenant_index, uid) -> TenantSpec``
        replacing the built-in tiny PSO/Ackley workload (the conductor
        still applies the plan's lane faults on top).
    :param member_kwargs: extra :class:`~evox_tpu.service.ServiceDaemon`
        kwargs for every member build (e.g. ``compact_records``).
    :param router_kwargs: extra :class:`~evox_tpu.service.TenantRouter`
        kwargs.
    :param slos: feed each member :func:`~evox_tpu.obs.default_slos`
        so the run ends with a real burn-rate report (``False`` to skip).
    :param audit_every: audit cadence in rounds.
    :param max_drain_rounds: fault-free rounds allowed after the plan to
        let every tenant finish before the run is declared wedged.
    """

    EVENT_LOG = "chaos_events.jsonl"
    REPORT = "chaos_report.json"

    def __init__(
        self,
        root: Any,
        plan: ChaosPlan,
        *,
        spec_factory: Callable[[int, int], TenantSpec] | None = None,
        member_kwargs: Mapping[str, Any] | None = None,
        router_kwargs: Mapping[str, Any] | None = None,
        slos: bool = True,
        audit_every: int = 1,
        recorder: FlightRecorder | None = None,
        exec_cache: ExecutableCache | None = None,
        max_drain_rounds: int = 200,
    ):
        plan.validate()
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.plan = plan
        self.spec_factory = spec_factory
        self.member_kwargs = dict(member_kwargs or {})
        self.router_kwargs = dict(router_kwargs or {})
        self.slos = bool(slos)
        self.audit_every = max(1, int(audit_every))
        self.max_drain_rounds = int(max_drain_rounds)
        self.exec_cache = (
            exec_cache
            if exec_cache is not None
            else ExecutableCache(self.root / "exec")
        )
        self.recorder = recorder or FlightRecorder(
            self.root / "postmortems", run_id=plan.digest()[:12]
        )
        self.members: dict[int, ServiceMember] = {}
        self.router: TenantRouter | None = None
        self.round = -1
        self.rounds_run = 0
        self.acks: list[dict[str, Any]] = []
        self.injected: list[dict[str, Any]] = []
        self.violations: list[InvariantViolation] = []
        self.pending: list[int] = []
        self.forgotten: set[str] = set()
        self._completed: set[str] = set()
        self._prev_counters: dict[str, float] = {}
        # Injected-fault sources drained into the canonical event journal:
        # ``(source, epoch, injector, events_seen)``.  Epochs count link /
        # store rebuilds so re-fired schedules stay distinguishable.
        self._injectors: list[dict[str, Any]] = []
        self._builds: dict[str, int] = {}
        self._wire: dict[int, FaultyTransport] = {}
        self._partitions: dict[int, int] = {}
        self._straggles: dict[int, tuple[int, float]] = {}

    # -- fleet construction --------------------------------------------------
    def _track_injector(self, source: str, injector: Any) -> int:
        epoch = self._builds.get(source, 0)
        self._builds[source] = epoch + 1
        self._injectors.append(
            {"source": source, "epoch": epoch, "obj": injector, "seen": 0}
        )
        return epoch

    def _build_member(self, index: int) -> ServiceMember:
        kwargs: dict[str, Any] = dict(
            lanes_per_pack=self.plan.lanes_per_pack,
            segment_steps=self.plan.segment_steps,
            seed=0,
            exec_cache=self.exec_cache,
        )
        if self.slos:
            kwargs["slos"] = default_slos()
        kwargs.update(self.member_kwargs)
        store_kwargs = self.plan.store_faults.get(f"member:{index}")
        if store_kwargs:
            store = FaultyStore(**store_kwargs)
            self._track_injector(f"store:member:{index}", store)
            kwargs["store"] = store
        member = ServiceMember(
            index,
            self.root / f"m{index}",
            heartbeat_dir=self.root / "beats",
            **kwargs,
        )
        member.daemon.chaos = self
        return member

    def _rewire(self, index: int, *, fresh_wire: bool) -> None:
        """Compose the member's link: base member → wire injector →
        straggle wrapper → partition wrapper (outermost wins)."""
        if self.router is None:  # pragma: no cover - internal misuse
            raise RuntimeError("conductor fleet is not built yet")
        base: Any = self.router.members[index]
        link: Any = base
        wire_kwargs = self.plan.wire_faults.get(str(index))
        if wire_kwargs:
            if fresh_wire or index not in self._wire:
                transport = FaultyTransport(base, **wire_kwargs)
                self._track_injector(f"wire:{index}", transport)
                self._wire[index] = transport
            else:
                self._wire[index].inner = base
            link = self._wire[index]
        straggle = self._straggles.get(index)
        if straggle is not None:
            wrapper = _StragglerLink(link, straggle[1])
            self._track_injector(f"straggle:{index}", wrapper)
            link = wrapper
        if index in self._partitions:
            wrapper = _PartitionedLink(index)
            self._track_injector(f"partition:{index}", wrapper)
            link = wrapper
        self.router.links[index] = link

    def _build_router(self) -> TenantRouter:
        kwargs: dict[str, Any] = dict(
            fleet_dead_after=300.0,
            fleet_start_grace=0.0,
        )
        kwargs.update(self.router_kwargs)
        router = TenantRouter(
            self.root / "router",
            [self.members[i] for i in sorted(self.members)],
            **kwargs,
        )
        store_kwargs = self.plan.store_faults.get("router")
        if store_kwargs:
            store = FaultyStore(**store_kwargs)
            self._track_injector("store:router", store)
            router.journal.close()
            router.journal = RequestJournal(
                router.root / TenantRouter.JOURNAL_NAME, store=store
            )
            if router.controller is not None:
                router.controller.journal = router.journal
        router.chaos = self
        self.router = router
        for index in self.members:
            self._rewire(index, fresh_wire=True)
        _silent(router.start)
        return router

    # -- plan ops ------------------------------------------------------------
    def _record(self, **event: Any) -> None:
        self.injected.append(dict(event))

    def _kill_member(self, index: int) -> None:
        """SIGKILL as abandonment: the old object is dropped with no
        shutdown path, a fresh member is rebuilt over the same root and
        replays its own journal."""
        if self.router is None:  # pragma: no cover - internal misuse
            raise RuntimeError("conductor fleet is not built yet")
        self.members.pop(index, None)
        member = self._build_member(index)
        self.members[index] = member
        self.router._register(member)
        self.router._dead.discard(index)
        self._rewire(index, fresh_wire=True)
        _silent(member.start)

    def _kill_router(self) -> None:
        """SIGKILL the control plane: abandon the router object and
        rebuild over the same journal — placements must replay."""
        self.router = None
        self._build_router()

    def _apply_event(self, ev: Mapping[str, Any]) -> None:
        op = str(ev["op"])
        if op == "kill-member":
            index = int(ev["member"])
            self._record(round=self.round, source="plan", kind=op,
                         member=index)
            self._kill_member(index)
        elif op == "kill-router":
            self._record(round=self.round, source="plan", kind=op)
            self._kill_router()
        elif op == "partition-member":
            index = int(ev["member"])
            self._record(round=self.round, source="plan", kind=op,
                         member=index, until=int(ev["until"]))
            self._partitions[index] = int(ev["until"])
            self._rewire(index, fresh_wire=False)
        elif op == "straggle-member":
            index = int(ev["member"])
            self._record(round=self.round, source="plan", kind=op,
                         member=index, until=int(ev["until"]),
                         delay_seconds=float(ev["delay_seconds"]))
            self._straggles[index] = (
                int(ev["until"]),
                float(ev["delay_seconds"]),
            )
            self._rewire(index, fresh_wire=False)

    def _expire_windows(self) -> None:
        for index, until in sorted(self._partitions.items()):
            if until <= self.round:
                del self._partitions[index]
                self._record(round=self.round, source="plan",
                             kind="partition-end", member=index)
                self._rewire(index, fresh_wire=False)
        for index, (until, _seconds) in sorted(self._straggles.items()):
            if until <= self.round:
                del self._straggles[index]
                self._record(round=self.round, source="plan",
                             kind="straggle-end", member=index)
                self._rewire(index, fresh_wire=False)

    def _drain_injectors(self) -> None:
        for entry in self._injectors:
            events = entry["obj"].events
            for index, kind in events[entry["seen"]:]:
                self._record(
                    round=self.round,
                    source=entry["source"],
                    epoch=entry["epoch"],
                    index=int(index),
                    kind=str(kind),
                )
            entry["seen"] = len(events)

    # -- workload ------------------------------------------------------------
    def tenant_id(self, index: int) -> str:
        return f"c{int(index):05d}"

    def _spec(self, index: int) -> TenantSpec:
        uid = int(index)
        if self.spec_factory is not None:
            spec = self.spec_factory(index, uid)
        else:
            import numpy as np

            from ..algorithms import PSO
            from ..problems.numerical import Ackley

            dim = 4
            spec = TenantSpec(
                self.tenant_id(index),
                PSO(8, -32.0 * np.ones(dim), 32.0 * np.ones(dim)),
                Ackley(),
                n_steps=self.plan.n_steps,
                uid=uid,
            )
        lane_spec = self.plan.lane_faults.get(str(index))
        if lane_spec:
            from dataclasses import replace

            spec = replace(
                spec,
                problem=FaultyProblem(
                    spec.problem, lane_faults={spec.uid: dict(lane_spec)}
                ),
            )
        return spec

    def _try_submit(self, index: int) -> bool:
        if self.router is None:  # pragma: no cover - internal misuse
            raise RuntimeError("conductor fleet is not built yet")
        spec = self._spec(index)
        try:
            record = _silent(self.router.submit, spec)
        except AdmissionError:
            # Retryable by contract: the placement (if journaled) is
            # reused by the retry next round — never re-minted.
            return False
        self.acks.append(
            {
                "tenant_id": spec.tenant_id,
                "uid": int(record.uid),
                "kind": "submit",
                "round": int(self.round),
            }
        )
        return True

    # -- auditing ------------------------------------------------------------
    def _audit(self) -> list[InvariantViolation]:
        if self.router is None:  # pragma: no cover - internal misuse
            raise RuntimeError("conductor fleet is not built yet")
        counters = {
            "conductor.acks": float(len(self.acks)),
            "conductor.injected": float(len(self.injected)),
            "conductor.rounds": float(self.rounds_run),
        }
        ctx = build_audit_context(
            self.router,
            acks=self.acks,
            round=self.round,
            forgotten=self.forgotten,
            counters=counters,
            previous_counters=self._prev_counters,
        )
        self._completed = set(ctx.completed)
        self._last_slo_reports = dict(ctx.slo_reports)
        self._prev_counters = dict(ctx.counters)
        found = audit_invariants(ctx)
        self.recorder.record_rows(
            {
                "chaos_round": [float(self.round)],
                "chaos_acks": [float(len(self.acks))],
                "chaos_injected": [float(len(self.injected))],
                "chaos_live_tenants": [
                    float(len(ctx.placements) - len(ctx.completed))
                ],
                "chaos_violations": [
                    float(len(self.violations) + len(found))
                ],
            },
            executed=1,
            start_generation=max(0, self.round),
        )
        for violation in found:
            self.violations.append(violation)
            self.recorder.dump(
                "invariant", detail=violation.to_json(), force=True
            )
        self._publish_gauges()
        return found

    def _publish_gauges(self) -> None:
        if self.router is None:
            return
        self.router._gauge(
            "evox_chaos_rounds",
            float(self.rounds_run),
            "Chaos scheduling rounds conducted.",
        )
        self.router._gauge(
            "evox_chaos_injected_events",
            float(len(self.injected)),
            "Faults injected by the chaos conductor, lifetime.",
        )
        self.router._gauge(
            "evox_chaos_invariant_violations",
            float(len(self.violations)),
            "Invariant violations detected by the chaos audit.",
        )
        self.router._gauge(
            "evox_chaos_pending_submissions",
            float(len(self.pending)),
            "Tenants awaiting a successful acked submission.",
        )

    def _write_event_log(self) -> Path:
        path = self.root / self.EVENT_LOG
        lines = [_canonical_json(event) for event in self.injected]
        text = "\n".join(lines)
        if text:
            text += "\n"
        atomic_write_text(path, text)
        return path

    # -- the run -------------------------------------------------------------
    def _round(self, r: int, new_tenants: list[int]) -> None:
        self.round = r
        self.rounds_run += 1
        self._expire_windows()
        if r < self.plan.rounds:
            for ev in self.plan.events:
                if int(ev["round"]) == r:
                    self._apply_event(ev)
        self.pending.extend(new_tenants)
        self.pending = [t for t in self.pending if not self._try_submit(t)]
        if self.router is None:  # pragma: no cover - internal misuse
            raise RuntimeError("conductor fleet is not built yet")
        _silent(self.router.step)
        self._drain_injectors()
        if r % self.audit_every == 0:
            self._audit()
            self._write_event_log()

    def _all_done(self) -> bool:
        return not self.pending and all(
            self.tenant_id(t) in self._completed
            for t in range(self.plan.tenants)
        )

    def run(self) -> ChaosReport:
        """Conduct the plan, audit continuously, drain to completion,
        and return (and persist) the report."""
        started = time.monotonic()
        self._last_slo_reports: dict[str, Any] = {}
        for index in range(self.plan.members):
            self.members[index] = self._build_member(index)
        self._build_router()
        schedule: dict[int, list[int]] = {}
        for tenant, r in enumerate(self.plan.submit_rounds):
            schedule.setdefault(int(r), []).append(tenant)
        for r in range(self.plan.rounds):
            self._round(r, schedule.get(r, []))
        extra = 0
        while extra < self.max_drain_rounds and not self._all_done():
            self._round(self.plan.rounds + extra, [])
            extra += 1
        self._audit()
        event_log = self._write_event_log()
        digest = hashlib.sha256(event_log.read_bytes()).hexdigest()
        worst: float | None = None
        for rows in self._last_slo_reports.values():
            for row in rows:
                burn = row.get("burn_rate")
                if burn is not None and (worst is None or burn > worst):
                    worst = float(burn)
        report = ChaosReport(
            plan_name=self.plan.name,
            plan_digest=self.plan.digest(),
            seed=self.plan.seed,
            rounds_run=self.rounds_run,
            tenants=self.plan.tenants,
            completed=len(self._completed),
            acks=len(self.acks),
            pending=len(self.pending),
            injected_events=len(self.injected),
            violations=[v.to_json() for v in self.violations],
            event_log=str(event_log),
            event_log_sha256=digest,
            slo_burn_report={
                "worst_burn_rate": worst,
                "scopes": self._last_slo_reports,
            },
            counters=dict(self._prev_counters),
            elapsed_seconds=time.monotonic() - started,
        )
        atomic_write_text(
            self.root / self.REPORT,
            json.dumps(report.to_json(), indent=2, sort_keys=True),
        )
        return report

    # -- statusz face --------------------------------------------------------
    def statusz_payload(self) -> dict[str, Any]:
        """The ``chaos`` section the attached router/daemon statusz (and
        the ``evoxtop`` soak strip) renders."""
        worst: float | None = None
        for rows in getattr(self, "_last_slo_reports", {}).values():
            for row in rows:
                burn = row.get("burn_rate")
                if burn is not None and (worst is None or burn > worst):
                    worst = float(burn)
        return {
            "plan": self.plan.name,
            "digest": self.plan.digest()[:12],
            "seed": self.plan.seed,
            "round": self.round,
            "rounds": self.plan.rounds,
            "injected_events": len(self.injected),
            "violations": len(self.violations),
            "acks": len(self.acks),
            "pending": len(self.pending),
            "completed": len(self._completed),
            "live_tenants": max(0, len(self.acks) - len(self._completed)),
            "worst_burn_rate": worst,
        }

    def close(self) -> None:
        if self.router is not None:
            self.router.close()
        for member in self.members.values():
            member.close()
