"""Construction-time fault-schedule audit: :func:`validate_schedule`.

One helper behind every injector's loud-failure contract.
:class:`~evox_tpu.resilience.FaultyProblem` grew the pattern (PR 8's
``_validate_schedules``); :class:`~evox_tpu.resilience.FaultyStore`,
:class:`~evox_tpu.resilience.FaultyTransport`, and the chaos plan DSL
(:class:`~evox_tpu.resilience.chaos.ChaosPlan`) all route through here, so
a malformed fault plan — a negative index, an index scheduled for two
incompatible fates, an unknown field — raises a ``ValueError`` naming the
field at construction, never a silent no-op or a confusing failure deep
inside the run it was meant to orchestrate.

Stdlib-only: the wire-side injector (``transport.py``) must stay cheap to
import in a client process that never touches jax.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

__all__ = ["validate_schedule"]


def validate_schedule(
    name: str,
    *,
    indices: Mapping[str, Any] | None = None,
    nonneg: Mapping[str, float] | None = None,
    exclusive: Sequence[tuple[str, str]] = (),
    fields: Mapping[str, Any] | None = None,
    known: Sequence[str] | None = None,
) -> dict[str, frozenset]:
    """Audit one fault plan at construction time.

    :param name: the injector/plan name, for error messages.
    :param indices: ``{field: iterable-of-ints}`` 0-based schedules; a
        negative index raises.  Returns each as a ``frozenset`` so
        constructors can assign the normalized form directly.
    :param nonneg: ``{field: scalar}`` parameters that must be ``>= 0``.
    :param exclusive: pairs of schedule fields whose index sets must not
        overlap — one attempt cannot take two fates (a save cannot both
        crash pre-publish and tear its published bytes; a request cannot
        be both never-delivered and have its reply dropped; a member
        cannot be SIGKILLed inside its own partition window).
    :param fields: a plan dict to check for unknown keys against
        ``known`` (the DSL-ingestion path; omit for plain constructors).
    :param known: the complete set of valid field names for ``fields``.
    :returns: ``{field: frozenset(int)}`` for every entry of ``indices``.
    """
    if fields is not None and known is not None:
        unknown = sorted(set(fields) - set(known))
        if unknown:
            raise ValueError(
                f"{name} has unknown field(s) {unknown}; valid fields are "
                f"{sorted(known)}"
            )
    normalized: dict[str, frozenset] = {}
    for field, values in (indices or {}).items():
        cast = frozenset(int(v) for v in values)
        bad = sorted(v for v in cast if v < 0)
        if bad:
            raise ValueError(
                f"{name}.{field} schedules 0-based indices; got negative "
                f"index(es) {bad}"
            )
        normalized[field] = cast
    for field, value in (nonneg or {}).items():
        if value < 0:
            raise ValueError(f"{name}.{field} must be >= 0, got {value}")
    for a, b in exclusive:
        overlap = normalized.get(a, frozenset()) & normalized.get(
            b, frozenset()
        )
        if overlap:
            raise ValueError(
                f"conflicting {name} schedules: {a} and {b} both fire at "
                f"index(es) {sorted(overlap)} — one attempt cannot take "
                f"two fates"
            )
    return normalized
