"""Automatic restart policies for unhealthy evolutionary runs.

When a :class:`~evox_tpu.resilience.HealthProbe` flags a degenerate search
(non-finite state, diversity collapse, step-size blow-up, stagnation — see
``health.py``), the supervising :class:`~evox_tpu.resilience.ResilientRunner`
applies one of these policies instead of burning the remaining budget on a
dead run.  Restart strategies with adapted population sizes are the standard
remedy in large-scale ES (IPOP-CMA-ES and descendants; see arXiv:2409.11765
for the massively-parallel variant this layer anticipates):

* :class:`RollbackToCheckpoint` — reload an earlier checkpoint (the PR-1
  checkpoint layer) and **perturb every PRNG stream** (``fold_in`` with the
  restart index) so the retry explores a different trajectory from a known-
  good state.  The cheapest policy; right for transient degeneration
  (a stagnation plateau, a corrupted buffer that a re-run heals).
* :class:`ReinitLargerPopulation` — IPOP-style: build a fresh algorithm with
  the population grown by ``growth_factor``, re-``setup`` from a perturbed
  key, and preserve the incumbent best (injected as an elite into the new
  population / distribution mean).  Monitor best-so-far metrics carry over;
  the problem sub-state is preserved (it is evaluation infrastructure, not
  search state).
* :class:`PerturbAroundBest` — keep shapes, re-seed the population as a
  Gaussian cloud around the incumbent best (scaled to the search-space
  width) and reset stale fitness to worst.  Right when the search found a
  good basin but collapsed inside it.

**Determinism contract** (matching PR 1): a policy's output is a pure
function of ``(checkpointed state, restart index, lineage)`` — no wall
clock, no fresh entropy.  The runner records every fired restart as a
:class:`RestartEvent` in ``RunStats`` and in each checkpoint's manifest, so
a killed-and-resumed run replays the same decisions bit-identically
(``tests/test_health_restart.py``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Callable, Mapping

import jax
import jax.numpy as jnp

from ..core import State
from ..utils.checkpoint import load_state
from .health import _is_prng, _subtree

if TYPE_CHECKING:  # pragma: no cover - import cycle guard (runner imports us)
    from .health import HealthReport
    from .runner import ResilientRunner

__all__ = [
    "RestartPolicy",
    "RestartEvent",
    "RestartContext",
    "RollbackToCheckpoint",
    "ReinitLargerPopulation",
    "PerturbAroundBest",
    "perturb_prng_keys",
    "incumbent_best",
]


# -- shared helpers ----------------------------------------------------------


def perturb_prng_keys(tree: Any, salt: int) -> Any:
    """Fold ``salt`` into every PRNG-key leaf of ``tree``.

    Deterministic and collision-free per salt: two restarts with different
    indices produce disjoint downstream streams, and a replayed restart with
    the same index reproduces its stream exactly."""

    def fold(leaf):
        if _is_prng(leaf):
            return jax.random.fold_in(leaf, salt)
        return leaf

    return jax.tree_util.tree_map(fold, tree)


def _first_prng_key(tree: Any) -> jax.Array | None:
    """First PRNG-key leaf in deterministic (flatten-order) traversal."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if _is_prng(leaf):
            return leaf
    return None


def incumbent_best(state: Any) -> tuple[jax.Array | None, jax.Array | None]:
    """The best-so-far ``(solution, fitness)`` recoverable from a workflow
    state, in the minimizing fitness frame.

    Prefers the monitor's running top-k (monotone best-so-far, survives
    generations where the population regressed); falls back to the best
    **finite** entry of the algorithm's current ``fit``/``pop`` pair.
    Returns ``(None, None)`` when no finite incumbent exists (e.g.
    multi-objective states, or a fully-diverged population) — a policy
    must never re-seed around a NaN "best", or every restart would
    re-inject the very corruption it is recovering from."""
    mon = _subtree(state, "monitor")
    if mon is not None:
        sols = _subtree(mon, "topk_solutions")
        fits = _subtree(mon, "topk_fitness")
        if (
            sols is not None
            and fits is not None
            and getattr(sols, "ndim", 0) == 2
            and getattr(fits, "ndim", 0) == 1
            and fits.size > 0
            and bool(jnp.isfinite(fits[0]))
            and bool(jnp.all(jnp.isfinite(sols[0])))
        ):
            return sols[0], fits[0]
    algo = _subtree(state, "algorithm")
    algo = algo if algo is not None else state
    pop = _subtree(algo, "pop")
    fit = _subtree(algo, "fit")
    if (
        pop is not None
        and fit is not None
        and getattr(pop, "ndim", 0) == 2
        and getattr(fit, "ndim", 0) == 1
        and fit.size == pop.shape[0]
        and jnp.issubdtype(fit.dtype, jnp.floating)
    ):
        # Rank non-finite fitness (and rows of non-finite solutions) last.
        usable = jnp.isfinite(fit) & jnp.all(jnp.isfinite(pop), axis=1)
        masked = jnp.where(usable, fit, jnp.inf)
        i = jnp.argmin(masked)
        if bool(usable[i]):
            return pop[i], fit[i]
    return None, None


# -- events ------------------------------------------------------------------


@dataclass
class RestartEvent:
    """One fired restart, as recorded in ``RunStats.restarts`` and in every
    subsequent checkpoint manifest (JSON round-trip via
    :meth:`to_manifest`/:meth:`from_manifest` — satellite: restart lineage
    survives resume)."""

    generation: int
    policy: str
    restart_index: int
    reasons: list[str] = field(default_factory=list)
    detail: dict[str, Any] = field(default_factory=dict)

    def to_manifest(self) -> dict[str, Any]:
        """JSON-serializable form for the checkpoint manifest."""
        return {
            "generation": self.generation,
            "policy": self.policy,
            "restart_index": self.restart_index,
            "reasons": list(self.reasons),
            "detail": dict(self.detail),
        }

    @classmethod
    def from_manifest(cls, data: Mapping[str, Any]) -> "RestartEvent":
        """Inverse of :meth:`to_manifest`."""
        return cls(
            generation=int(data["generation"]),
            policy=str(data["policy"]),
            restart_index=int(data["restart_index"]),
            reasons=list(data.get("reasons", [])),
            detail=dict(data.get("detail", {})),
        )


@dataclass
class RestartContext:
    """Everything a policy may consult when applying a restart."""

    runner: "ResilientRunner"
    workflow: Any
    state: State
    generation: int
    report: "HealthReport"
    restart_index: int
    lineage: tuple[RestartEvent, ...] = ()
    # The controller trend decision that fired this restart, when the
    # verdict came from the control plane (``evox_tpu.control``) rather
    # than the threshold probe — ``None`` for probe-triggered restarts.
    # Policies may consult its evidence (e.g. scale a perturbation by the
    # measured stagnation slope); the runner also folds its action into
    # the RestartEvent's detail, so the lineage records which plane fired.
    decision: Any | None = None


# -- the policy interface ----------------------------------------------------


class RestartPolicy:
    """A deterministic recovery action for an unhealthy run.

    ``apply`` returns ``(state, generation, needs_init, detail)``:

    * ``state`` — the restarted workflow state the run continues from;
    * ``generation`` — the generation count the run resumes at (equal to
      ``ctx.generation`` unless the policy rolled time back);
    * ``needs_init`` — True when ``state`` is a pre-``init_step`` state
      (fresh setup) the runner must drive through one init segment before
      chunking resumes;
    * ``detail`` — JSON-serializable facts for the :class:`RestartEvent`.

    ``rebuild_template`` lets resume reconstruct the checkpoint-validation
    template after restarts that changed state *shapes* (population
    regrows); shape-preserving policies inherit the identity."""

    name: str = "restart"

    def apply(
        self, ctx: RestartContext
    ) -> tuple[State, int, bool, dict[str, Any]]:
        raise NotImplementedError

    def rebuild_template(
        self,
        workflow: Any,
        template: State,
        lineage: list[RestartEvent],
        runner: "ResilientRunner | None" = None,
    ) -> State:
        """Template a checkpoint written *after* ``lineage`` validates
        against.  Default: shapes unchanged, the caller's template."""
        del workflow, lineage, runner
        return template


class RollbackToCheckpoint(RestartPolicy):
    """Reload an earlier checkpoint and perturb every PRNG stream.

    The retry re-runs the rolled-back generations with ``fold_in``-perturbed
    keys, so it explores a *different* trajectory from a known-good state —
    the restart analogue of the PR-1 retry ladder.  When no earlier
    checkpoint survives (pruning, restart at the first boundary), the
    current state is perturbed in place (time does not roll back).

    :param back: how many checkpoint boundaries to roll back (1 = the
        boundary before the unhealthy one).  Clamped to the oldest
        retained checkpoint — size ``ResilientRunner(keep_checkpoints=...)``
        accordingly.
    :param salt: base value folded (offset by the restart index) into PRNG
        leaves; change it to decorrelate two otherwise identical retries.
    """

    name = "rollback"

    def __init__(self, back: int = 1, salt: int = 0x5EED):
        if back < 1:
            raise ValueError(f"back must be >= 1, got {back}")
        self.back = int(back)
        self.salt = int(salt)

    def apply(self, ctx: RestartContext):
        from ..utils.checkpoint import CheckpointError
        from .runner import _numbered_checkpoints

        candidates = [
            (gen, path)
            for gen, path in _numbered_checkpoints(ctx.runner.checkpoint_dir)
            if gen < ctx.generation
        ]
        state, gen, detail = None, ctx.generation, {"rolled_back_to": None}
        # Walk from the back-th candidate toward older ones: one unusable
        # file (torn, or a pre-upgrade schema) must degrade the rollback,
        # not abort the run ("one bad file cannot lose the run").
        start = max(len(candidates) - self.back, 0) if candidates else -1
        for i in range(start, -1, -1):
            cand_gen, path = candidates[i]
            try:
                # Digest-verify like the runner's own resume scan: a
                # bit-flipped rollback target must be skipped, not silently
                # restored into the "known-good" restart state.
                state = load_state(
                    path,
                    ctx.state,
                    allow_missing=True,
                    verify=getattr(ctx.runner, "verify_resume", True),
                )
            except (CheckpointError, ValueError) as e:
                ctx.runner._event(
                    f"rollback skipping unusable checkpoint {path.name}: {e}",
                    warn=True,
                )
                continue
            gen, detail = cand_gen, {"rolled_back_to": cand_gen}
            break
        if state is None:
            # No loadable earlier checkpoint: perturb in place (time does
            # not roll back).
            state = ctx.state
        state = perturb_prng_keys(state, self.salt + ctx.restart_index)
        return state, gen, False, detail


class ReinitLargerPopulation(RestartPolicy):
    """IPOP-style restart: fresh setup with a grown population, elite kept.

    Requires a workflow exposing a mutable ``.algorithm`` attribute and an
    ``init(key)`` state builder (``StdWorkflow`` does; distributed/sharded
    workflows are out of scope — the population re-shard would need mesh
    revalidation).  Across successive restarts the population compounds:
    ``pop * growth_factor ** k``, capped at ``max_pop_size``.

    What carries over from the unhealthy state:

    * the **incumbent best** — written into row 0 of the new population
      (or the new distribution ``mean`` for mean-based ES);
    * the monitor's best-so-far metrics (top-k, ``generation``,
      ``num_nonfinite``, ``num_restarts``, ``instance_id``);
    * the **problem sub-state** (evaluation infrastructure — e.g. a fault
      schedule's position — not search state).

    Everything else is rebuilt by ``algorithm.setup`` from a
    restart-index-perturbed PRNG key, so the regrown run is deterministic.

    :param algorithm_factory: ``pop_size -> Algorithm`` builder for the
        regrown algorithm (same hyperparameters, new population size).
        Resume needs the same factory configured to reconstruct templates.
    :param growth_factor: multiplicative population growth per restart
        (IPOP default 2.0).
    :param max_pop_size: hard cap on the regrown population (``None`` =
        uncapped).
    :param preserve_elite: inject the incumbent best into the new
        population/mean (on by default).
    :param salt: base PRNG fold value, offset by the restart index.
    """

    name = "reinit_larger_population"

    def __init__(
        self,
        algorithm_factory: Callable[[int], Any],
        growth_factor: float = 2.0,
        max_pop_size: int | None = None,
        preserve_elite: bool = True,
        salt: int = 0x1B0B,
    ):
        if growth_factor <= 1.0:
            raise ValueError(
                f"growth_factor must be > 1.0 (the population must grow), "
                f"got {growth_factor}"
            )
        if max_pop_size is not None and max_pop_size < 1:
            raise ValueError(f"max_pop_size must be >= 1, got {max_pop_size}")
        self.algorithm_factory = algorithm_factory
        self.growth_factor = float(growth_factor)
        self.max_pop_size = max_pop_size
        self.preserve_elite = preserve_elite
        self.salt = int(salt)

    # carried monitor keys: scalar/metric state that must survive a regrow.
    _CARRY_MONITOR = (
        "topk_solutions",
        "topk_fitness",
        "generation",
        "instance_id",
        "num_nonfinite",
        "num_shard_quarantines",
        "num_restarts",
        "num_preemptions",
    )

    def _new_pop_size(self, current: int) -> int:
        new_pop = max(int(round(current * self.growth_factor)), current + 1)
        if self.max_pop_size is not None:
            new_pop = min(new_pop, self.max_pop_size)
        return new_pop

    def _rebuild(self, workflow: Any, runner: "ResilientRunner", pop_size: int):
        if not hasattr(workflow, "algorithm"):
            raise ValueError(
                f"{self.name} needs a workflow with a mutable `.algorithm` "
                f"attribute (e.g. StdWorkflow); got {type(workflow).__name__}"
            )
        workflow.algorithm = self.algorithm_factory(pop_size)
        runner._rebind_workflow()

    def apply(self, ctx: RestartContext):
        algo = getattr(ctx.workflow, "algorithm", None)
        current = getattr(algo, "pop_size", None)
        if current is None:
            raise ValueError(
                f"{self.name} needs a workflow whose `.algorithm` exposes "
                f"`pop_size`; got {type(algo).__name__}"
            )
        new_pop = self._new_pop_size(int(current))
        best, _ = incumbent_best(ctx.state)

        key = _first_prng_key(ctx.state)
        if key is None:
            key = jax.random.key(self.salt)
        key = jax.random.fold_in(key, self.salt + ctx.restart_index)

        self._rebuild(ctx.workflow, ctx.runner, new_pop)
        fresh = getattr(ctx.workflow, "init", ctx.workflow.setup)(key)

        algo_state = _subtree(fresh, "algorithm")
        if algo_state is None:
            raise ValueError(
                f"{self.name} expects workflow.init() to return a state with "
                f"an 'algorithm' sub-state; got keys {list(fresh)}"
            )
        if self.preserve_elite and best is not None:
            pop = _subtree(algo_state, "pop")
            mean = _subtree(algo_state, "mean")
            if (
                pop is not None
                and getattr(pop, "ndim", 0) == 2
                and pop.shape[1] == best.shape[0]
            ):
                updates = {"pop": pop.at[0].set(best.astype(pop.dtype))}
                # Personal-best buffers sampled in setup() still point at
                # the pre-injection random row 0; keep them coherent so the
                # elite's (good) fitness never gets attributed to a
                # discarded position.
                lbl = _subtree(algo_state, "local_best_location")
                if lbl is not None and lbl.shape == pop.shape:
                    updates["local_best_location"] = lbl.at[0].set(
                        best.astype(lbl.dtype)
                    )
                algo_state = algo_state.replace(**updates)
            elif mean is not None and mean.shape == best.shape:
                algo_state = algo_state.replace(mean=best.astype(mean.dtype))

        state = fresh.replace(algorithm=algo_state)
        mon_state = _subtree(fresh, "monitor")
        old_mon = _subtree(ctx.state, "monitor")
        if old_mon is not None and isinstance(mon_state, State):
            carried = {
                k: old_mon[k]
                for k in self._CARRY_MONITOR
                if k in old_mon and k in mon_state
            }
            if carried:
                state = state.replace(monitor=mon_state.replace(**carried))
        old_problem = _subtree(ctx.state, "problem")
        if old_problem is not None and "problem" in fresh:
            state = state.replace(problem=old_problem)
        return state, ctx.generation, True, {"pop_size": new_pop}

    def rebuild_template(self, workflow, template, lineage, runner=None):
        events = [e for e in lineage if e.policy == self.name]
        if not events or runner is None:
            return template
        self._rebuild(workflow, runner, int(events[-1].detail["pop_size"]))
        # Only structure (shapes/dtypes/treedef) matters for a template;
        # the key value is irrelevant.
        return getattr(workflow, "init", workflow.setup)(jax.random.key(0))


class PerturbAroundBest(RestartPolicy):
    """Re-seed the population as a Gaussian cloud around the incumbent best.

    Shapes are preserved (no recompilation beyond PRNG perturbation): the
    new population is ``best + scale * width * N(0, 1)`` — ``width`` being
    the per-dimension search-space width when the algorithm exposes
    ``lb``/``ub`` bounds (samples are clipped back into them), else 1.0 —
    with the incumbent itself kept unperturbed in row 0 and stale fitness
    reset to worst so the next generation re-ranks from scratch.  Mean-based
    ES states (no ``pop``) get ``mean := best`` and, when the algorithm
    exposes a ``sigma_init``, a step-size reset.

    :param scale: cloud radius as a fraction of the search-space width.
    :param salt: base PRNG fold value, offset by the restart index.
    """

    name = "perturb_around_best"

    def __init__(self, scale: float = 0.1, salt: int = 0xBE57):
        if scale <= 0:
            raise ValueError(f"scale must be > 0, got {scale}")
        self.scale = float(scale)
        self.salt = int(salt)

    def apply(self, ctx: RestartContext):
        best, best_fit = incumbent_best(ctx.state)
        state = perturb_prng_keys(ctx.state, self.salt + ctx.restart_index)
        if best is None:
            return state, ctx.generation, False, {"note": "no incumbent; PRNG perturbation only"}

        algo_state = state["algorithm"] if "algorithm" in state else state
        algo = getattr(ctx.workflow, "algorithm", None)
        lb = getattr(algo, "lb", None)
        ub = getattr(algo, "ub", None)

        pop = _subtree(algo_state, "pop")
        detail: dict[str, Any] = {"scale": self.scale}
        if (
            pop is not None
            and getattr(pop, "ndim", 0) == 2
            and pop.shape[1] == best.shape[0]
        ):
            width = (
                (ub - lb).astype(pop.dtype)
                if lb is not None and ub is not None
                else jnp.ones((), pop.dtype)
            )
            noise_key = _first_prng_key(algo_state)
            if noise_key is None:
                noise_key = jax.random.key(self.salt)
            noise_key = jax.random.fold_in(noise_key, ctx.restart_index + 1)
            cloud = best.astype(pop.dtype) + self.scale * width * jax.random.normal(
                noise_key, pop.shape, dtype=pop.dtype
            )
            cloud = cloud.at[0].set(best.astype(pop.dtype))
            if lb is not None and ub is not None:
                cloud = jnp.clip(cloud, lb, ub)
            updates: dict[str, Any] = {"pop": cloud}
            # Stale per-position records belong to the COLLAPSED positions;
            # left in place they drag the fresh cloud straight back into
            # the collapse (a particle's personal best would still be the
            # old point, carrying its old score).  Re-anchor personal-best
            # locations on the cloud and worst-out the stale scores so the
            # next evaluation re-establishes them honestly.
            fit = _subtree(algo_state, "fit")
            if (
                fit is not None
                and getattr(fit, "ndim", 0) == 1
                and jnp.issubdtype(fit.dtype, jnp.floating)
            ):
                updates["fit"] = jnp.full_like(fit, jnp.inf)
            lbl = _subtree(algo_state, "local_best_location")
            lbf = _subtree(algo_state, "local_best_fit")
            if lbl is not None and lbl.shape == cloud.shape:
                updates["local_best_location"] = cloud.astype(lbl.dtype)
            if (
                lbf is not None
                and getattr(lbf, "ndim", 0) == 1
                and jnp.issubdtype(lbf.dtype, jnp.floating)
            ):
                updates["local_best_fit"] = jnp.full_like(lbf, jnp.inf)
            algo_state = algo_state.replace(**updates)
            detail["reseeded"] = "pop"
        else:
            mean = _subtree(algo_state, "mean")
            if mean is not None and mean.shape == best.shape:
                algo_state = algo_state.replace(mean=best.astype(mean.dtype))
                sigma = _subtree(algo_state, "sigma")
                sigma_init = getattr(algo, "sigma_init", None)
                if sigma is not None and sigma_init is not None:
                    algo_state = algo_state.replace(
                        sigma=jnp.asarray(sigma_init, dtype=sigma.dtype)
                        * jnp.ones_like(sigma)
                    )
                detail["reseeded"] = "mean"
            else:
                detail["reseeded"] = None

        if "algorithm" in state:
            state = state.replace(algorithm=algo_state)
        else:
            state = algo_state
        return state, ctx.generation, False, detail
