"""Fleet supervisor: survive host death, stragglers, and fleet resizing.

The per-process :class:`~evox_tpu.resilience.ResilientRunner` survives
everything that can happen *inside* a process — backend loss, hangs, bad
checkpoints, preemption signals.  What it cannot survive is the failure
mode unique to multi-host (``jax.distributed``) fleets: a **peer** dying.
SPMD collectives are all-or-nothing — when one host SIGKILLs, every
survivor wedges in its next all-gather, no exception is raised anywhere,
and the job burns budget until an external actor intervenes.  The
reference framework inherits ``torchrun``'s answer (abort the world); a
long evolutionary run deserves better, because PR 4's elastic re-mesh
invariant means the *surviving* hosts are a perfectly good fleet: the
checkpointed state is global and the PRNG streams are topology-invariant,
so the run continues bit-identically at any world size.

:class:`FleetSupervisor` is that external actor — a plain-Python process
(not a fleet member; it never touches a collective) that:

1. **launches** N worker processes with a fresh coordinator address and
   the ``EVOX_TPU_FLEET_*`` environment contract
   (:func:`~evox_tpu.parallel.bootstrap_fleet` consumes it);
2. **watches** two independent signals — worker exit codes, and the
   heartbeat plane (:class:`~evox_tpu.parallel.FleetHealth`) the workers'
   runners publish into — and renders per-host verdicts: **dead** (exit /
   stale beat), **wedged** (alive, frozen progress — a collective stuck on
   a dead peer, or a coordinator partition), **slow** (self-reported
   eval-deadline trips — the cross-host straggler);
3. **stops the survivors** on any unhealthy verdict: SIGTERM first (the
   workers' :class:`~evox_tpu.resilience.PreemptionGuard` turns it into a
   graceful boundary stop with an emergency checkpoint where reachable),
   then SIGKILL after a grace window (a worker wedged inside a gloo/ICI
   collective cannot run Python signal handlers; its last boundary
   checkpoint is already durable, thanks to the single-writer discipline);
4. **relaunches** on the surviving process count — a new coordinator, a
   new rendezvous, ``num_processes - removed`` workers — and the workers'
   runners auto-resume from the shared checkpoint directory, re-meshing
   the state onto the smaller world.  The resumed trajectory is
   bit-identical to an uninterrupted run at that world size
   (``tests/test_multihost.py``, the chaos acceptance).

The supervisor is deliberately dumb about *what* the workers compute: the
``command`` callable maps a :class:`WorkerSpec` to an argv, and everything
else — algorithm, mesh, runner configuration — lives in the worker script.
Worker contract: exit ``0`` on completion; any other exit (or silence on
the heartbeat plane) is a failure verdict.  Exit code ``75``
(``EX_TEMPFAIL`` — the conventional "preempted, resume me" code) is how a
worker acknowledges a graceful stop; the supervisor treats it as expected
during a shutdown it initiated, and as a failure otherwise.
"""

from __future__ import annotations

import os
import socket
import subprocess
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Mapping, Sequence, Union

from ..obs.aggregate import FleetAggregator
from ..obs.endpoint import IntrospectionEndpoint
from ..obs.plane import Observability, resolve_obs
from ..parallel.multihost import (
    FLEET_ENV_ATTEMPT,
    FLEET_ENV_COORDINATOR,
    FLEET_ENV_HEARTBEAT_DIR,
    FLEET_ENV_NUM_PROCESSES,
    FLEET_ENV_PROCESS_ID,
    FleetHealth,
    FleetReport,
)

__all__ = [
    "FleetSupervisor",
    "FleetError",
    "FleetStats",
    "WorkerSpec",
    "EX_PREEMPTED",
    "free_coordinator_port",
]

# The conventional "temporarily failed, try again" exit code (sysexits.h
# EX_TEMPFAIL): a worker that was asked to stop (SIGTERM -> Preempted ->
# emergency checkpoint) exits with this to say "resumable, not broken".
EX_PREEMPTED = 75


class FleetError(RuntimeError):
    """The fleet could not be driven to completion: the relaunch budget is
    spent, the world shrank below ``min_processes``, or an attempt blew its
    wall-clock timeout.  ``stats`` carries the full event history."""

    def __init__(self, message: str, stats: "FleetStats | None" = None):
        super().__init__(message)
        self.stats = stats


def free_coordinator_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port for the fleet coordinator.  Raises
    ``OSError`` where binding is impossible — callers (and the test lane)
    use that to skip cleanly on sandboxes without loopback networking."""
    with socket.socket() as s:
        s.bind((host, 0))
        return int(s.getsockname()[1])


@dataclass(frozen=True)
class WorkerSpec:
    """Everything one worker process needs to join its fleet attempt."""

    process_id: int
    num_processes: int
    coordinator: str
    attempt: int
    heartbeat_dir: str
    checkpoint_dir: str

    def env(self) -> dict[str, str]:
        """The ``EVOX_TPU_FLEET_*`` environment contract
        :func:`~evox_tpu.parallel.bootstrap_fleet` consumes."""
        return {
            FLEET_ENV_COORDINATOR: self.coordinator,
            FLEET_ENV_NUM_PROCESSES: str(self.num_processes),
            FLEET_ENV_PROCESS_ID: str(self.process_id),
            FLEET_ENV_HEARTBEAT_DIR: self.heartbeat_dir,
            FLEET_ENV_ATTEMPT: str(self.attempt),
        }


@dataclass
class FleetEvent:
    """One supervisor decision, for the post-mortem record."""

    attempt: int
    kind: str  # launch | host-death | wedged | straggler | relaunch | complete | stop
    detail: str


@dataclass
class FleetStats:
    """Observable record of what the supervisor did during :meth:`run`."""

    attempts: int = 0
    completed: bool = False
    world_sizes: list[int] = field(default_factory=list)
    removed_hosts: list[tuple[int, int, str]] = field(default_factory=list)
    host_deaths: int = 0
    hosts_quarantined: int = 0
    events: list[FleetEvent] = field(default_factory=list)
    exit_codes: list[dict[int, int | None]] = field(default_factory=list)
    last_report: FleetReport | None = None

    @property
    def final_world_size(self) -> int | None:
        return self.world_sizes[-1] if self.world_sizes else None


class _PopenWorker:
    """Default worker handle: a subprocess with its output teed to a log
    file under the heartbeat directory (the supervisor's flight recorder)."""

    def __init__(self, argv: Sequence[str], env: Mapping[str, str], log: Path):
        # A live subprocess stdout/stderr sink cannot be staged-and-renamed:
        # the OS writes into it for the worker's whole lifetime.  Loss past
        # the last flush on a crash is acceptable flight-recorder semantics.
        self._log = open(log, "wb")  # graftlint: disable=GL009
        self.proc = subprocess.Popen(
            list(argv), env=dict(env), stdout=self._log, stderr=self._log
        )

    @property
    def pid(self) -> int:
        return self.proc.pid

    def poll(self) -> int | None:
        rc = self.proc.poll()
        if rc is not None and not self._log.closed:
            self._log.close()
        return rc

    def terminate(self) -> None:
        try:
            self.proc.terminate()
        except OSError:  # pragma: no cover - already gone
            pass

    def kill(self) -> None:
        try:
            self.proc.kill()
        except OSError:  # pragma: no cover - already gone
            pass

    def wait(self, timeout: float | None = None) -> int | None:
        try:
            rc = self.proc.wait(timeout)
        except subprocess.TimeoutExpired:
            return None
        if not self._log.closed:
            self._log.close()
        return rc


def _default_spawn(
    argv: Sequence[str], env: Mapping[str, str], spec: WorkerSpec
) -> _PopenWorker:
    log = Path(spec.heartbeat_dir) / (
        f"worker_a{spec.attempt:02d}_p{spec.process_id:02d}.log"
    )
    log.parent.mkdir(parents=True, exist_ok=True)
    return _PopenWorker(argv, env, log)


class FleetSupervisor:
    """Launch, watch, shrink, and relaunch a ``jax.distributed`` fleet.

    Usage::

        def command(spec):                       # argv for one worker
            return [sys.executable, "worker.py"]

        sup = FleetSupervisor(
            command, num_processes=4,
            checkpoint_dir="ckpts/run1",
            dead_after=5.0, eval_deadline=2.0,
        )
        stats = sup.run()       # survives host death; FleetError when not

    The worker script calls ``bootstrap_fleet()`` (which reads the
    environment this supervisor publishes), runs its
    :class:`~evox_tpu.resilience.ResilientRunner` against the shared
    ``checkpoint_dir`` with a
    :class:`~evox_tpu.parallel.HostHeartbeat` pointed at
    ``heartbeat_dir``, and exits 0 — see
    ``docs/guide/distributed.md#multi-host-fleets`` for a complete worker.

    Degenerate path: ``num_processes=1`` supervises a single worker with
    no coordinator (``WorkerSpec.coordinator`` is empty, so
    ``bootstrap_fleet`` no-ops) — the same script runs fleet-less, and the
    supervisor still provides crash-relaunch supervision.
    """

    def __init__(
        self,
        command: Callable[[WorkerSpec], Sequence[str]],
        num_processes: int,
        *,
        checkpoint_dir: Union[str, Path],
        heartbeat_dir: Union[str, Path, None] = None,
        coordinator_host: str = "127.0.0.1",
        env: Mapping[str, str] | None = None,
        poll_interval: float = 0.2,
        dead_after: float = 5.0,
        stall_after: float | None = None,
        eval_deadline: float | None = None,
        start_grace: float = 120.0,
        grace_seconds: float = 10.0,
        min_processes: int = 1,
        max_relaunches: int = 4,
        attempt_timeout: float | None = None,
        on_event: Callable[[str], None] | None = None,
        spawn: Callable[..., Any] | None = None,
        obs: Union["Observability", bool, None] = None,
        endpoint: Union[int, bool, None] = None,
        endpoint_host: str = "127.0.0.1",
        healthz_url: str | None = None,
        healthz_timeout: float = 2.0,
    ):
        """
        :param command: maps a :class:`WorkerSpec` to the argv of one
            worker process.  The spec's environment contract is published
            *in addition* to ``env`` — most commands are therefore just
            ``lambda spec: [sys.executable, "worker.py"]``.
        :param num_processes: initial world size.
        :param checkpoint_dir: the fleet's shared checkpoint directory
            (single-writer: worker 0 publishes, everyone resumes from it).
        :param heartbeat_dir: where workers publish
            :class:`~evox_tpu.parallel.HostHeartbeat` beats and the
            supervisor writes per-worker logs; defaults to
            ``<checkpoint_dir>/heartbeats``.
        :param coordinator_host: address workers rendezvous on; each
            attempt binds a fresh OS-assigned port.
        :param env: base environment for workers (default: inherit the
            supervisor's).  Per-worker fleet variables are layered on top.
        :param poll_interval: supervisor wake-up period.
        :param dead_after: heartbeat staleness (seconds) before a host is
            declared dead (see :class:`~evox_tpu.parallel.FleetHealth`).
        :param stall_after: seconds of frozen generation progress before a
            host is declared wedged; ``None`` disables (exit codes still
            catch outright deaths).
        :param eval_deadline: per-host deadline verdict threshold —
            heartbeats reporting ``deadline_trips`` (or segment seconds
            above this) mark the host slow, and the supervisor quarantines
            it at the next stop: the relaunched world excludes it.
        :param start_grace: seconds a freshly-launched attempt may take to
            produce first heartbeats (bootstrap + first compile).
        :param grace_seconds: SIGTERM-to-SIGKILL escalation window when
            stopping survivors.  Workers reachable at a segment boundary
            stop gracefully (emergency checkpoint) inside it; workers
            wedged in a dead collective are SIGKILLed after it — their
            last boundary checkpoint is already durable.
        :param min_processes: smallest world the run may shrink to; going
            below raises :class:`FleetError`.
        :param max_relaunches: relaunch budget; exhausting it raises
            :class:`FleetError`.
        :param attempt_timeout: optional wall-clock budget per attempt —
            a deadlocked fleet becomes a loud :class:`FleetError`, never
            a silent hang (the ``--multihost`` test lane leans on this).
        :param on_event: optional sink for one human-readable line per
            supervisor decision.
        :param spawn: worker factory ``(argv, env, spec) -> handle`` with
            ``poll/terminate/kill/wait/pid`` — injectable so the
            supervisor's decision logic is unit-testable without real
            subprocesses; defaults to ``subprocess.Popen`` with logs under
            ``heartbeat_dir``.
        :param obs: the :class:`~evox_tpu.obs.Observability` plane: every
            supervisor decision (``launch``/``host-death``/``wedged``/
            ``straggler``/``fleet-stall``/``relaunch``/``complete``)
            publishes a structured ``fleet`` event alongside the legacy
            ``on_event`` string, and ``evox_fleet_*`` metrics (attempts,
            host deaths, quarantines, world size) feed the plane's
            registry.  ``None`` builds a default plane; ``False``
            disables instrumentation.
        :param endpoint: arm the supervisor's own introspection endpoint
            (:class:`~evox_tpu.obs.IntrospectionEndpoint`, serving for
            the duration of :meth:`run`): an ``int`` binds that port,
            ``True`` an OS-assigned one.  ``/metrics`` is the
            fleet-aggregated view — every worker's heartbeat metrics
            merged by a :class:`~evox_tpu.obs.FleetAggregator` into the
            supervisor's registry (counters summed relaunch-monotone,
            gauges per ``process_index``, dead hosts ``stale="true"``) —
            ``/healthz`` renders the live per-host verdicts (non-200 on
            dead/wedged/slow), ``/statusz`` the supervision record.
        :param endpoint_host: endpoint bind address (default loopback).
        :param healthz_url: optional external ``/healthz`` to CONSUME:
            each watch poll GETs it, and a non-200 response's
            ``dead``/``wedged``/``slow`` host lists merge into this
            supervisor's own verdicts — the seam that lets a daemon's
            (or any sidecar's) health view drive supervision.
            Unreachable endpoints warn once and are ignored: losing the
            health sidecar must never take down the fleet it watches.
        :param healthz_timeout: per-poll timeout for ``healthz_url``.
        """
        if num_processes < 1:
            raise ValueError(
                f"num_processes must be >= 1, got {num_processes}"
            )
        if min_processes < 1:
            raise ValueError(f"min_processes must be >= 1, got {min_processes}")
        if min_processes > num_processes:
            raise ValueError(
                f"min_processes ({min_processes}) cannot exceed "
                f"num_processes ({num_processes})"
            )
        if max_relaunches < 0:
            raise ValueError(
                f"max_relaunches must be >= 0, got {max_relaunches}"
            )
        self.command = command
        self.num_processes = int(num_processes)
        self.checkpoint_dir = Path(checkpoint_dir)
        self.heartbeat_dir = (
            Path(heartbeat_dir)
            if heartbeat_dir is not None
            else self.checkpoint_dir / "heartbeats"
        )
        self.coordinator_host = str(coordinator_host)
        self.env = dict(env) if env is not None else dict(os.environ)
        self.poll_interval = float(poll_interval)
        self.dead_after = float(dead_after)
        self.stall_after = None if stall_after is None else float(stall_after)
        self.eval_deadline = (
            None if eval_deadline is None else float(eval_deadline)
        )
        self.start_grace = float(start_grace)
        self.grace_seconds = float(grace_seconds)
        self.min_processes = int(min_processes)
        self.max_relaunches = int(max_relaunches)
        self.attempt_timeout = (
            None if attempt_timeout is None else float(attempt_timeout)
        )
        self.on_event = on_event
        self.spawn = spawn if spawn is not None else _default_spawn
        self.obs = resolve_obs(obs, run_id=Path(checkpoint_dir).name)
        self._metric_cursor: dict[str, float] = {}
        self.stats = FleetStats()
        self.healthz_url = healthz_url
        self.healthz_timeout = float(healthz_timeout)
        self._healthz_warned = False
        # Fleet aggregation merges INTO the supervisor's registry (one
        # scrape = supervisor series + every host's), safe because the
        # supervisor never publishes the host-side series names itself.
        self.aggregator = FleetAggregator(
            registry=self.obs.registry if self.obs is not None else None
        )
        self._health: FleetHealth | None = None
        self.endpoint: IntrospectionEndpoint | None = None
        if endpoint is not None and endpoint is not False:
            self.endpoint = IntrospectionEndpoint(
                metrics=self._metrics_text,
                healthz=self._healthz,
                statusz=self._statusz,
                instrument=(
                    self.obs.registry if self.obs is not None else None
                ),
                host=endpoint_host,
                port=0 if endpoint is True else int(endpoint),
            )

    # -- events --------------------------------------------------------------
    # Supervisor decisions that mean something broke vs routine lifecycle.
    _WARN_KINDS = (
        "host-death",
        "wedged",
        "straggler",
        "fleet-stall",
        "stop",
        "healthz-unreachable",
    )

    def _event(self, attempt: int, kind: str, detail: str) -> None:
        self.stats.events.append(FleetEvent(attempt, kind, detail))
        if self.obs is not None:
            self.obs.event(
                "fleet",
                f"[fleet attempt {attempt}] {kind}: {detail}",
                severity="warning" if kind in self._WARN_KINDS else "info",
                attempt=attempt,
                kind=kind,
            )
            self.obs.counter(
                "evox_fleet_events_total",
                "Fleet supervisor decisions by kind.",
                kind=kind,
            ).inc()
            self._publish_metrics()
        if self.on_event is not None:
            self.on_event(f"[fleet attempt {attempt}] {kind}: {detail}")

    def _publish_metrics(self) -> None:
        """Sync FleetStats into the registry (delta-published against a
        cursor that resets with the stats, like the runner's — one shared
        ``counter_sync`` definition)."""
        s = self.stats
        for name, value, help in (
            ("evox_fleet_attempts_total", s.attempts, "Fleet attempts launched."),
            ("evox_fleet_host_deaths_total", s.host_deaths, "Workers lost to exits or stale heartbeats."),
            (
                "evox_fleet_quarantines_total",
                s.hosts_quarantined,
                "Hosts quarantined as slow/wedged (culprit-less stalls included).",
            ),
            (
                "evox_fleet_removed_hosts_total",
                len(s.removed_hosts),
                "Hosts removed from the fleet across attempts.",
            ),
        ):
            self.obs.registry.counter_sync(
                self._metric_cursor, name, value, help
            )
        if s.world_sizes:
            self.obs.gauge(
                "evox_fleet_world_size",
                "Process count of the current fleet attempt.",
            ).set(s.world_sizes[-1])

    # -- introspection (read-only providers + the consumed sidecar) ----------
    def _metrics_text(self) -> str:
        """The fleet-aggregated Prometheus text: fold the current beats
        (with the live attempt's verdicts for staleness) into the
        aggregator, then export.  Endpoint handler thread only."""
        from ..parallel.multihost import read_heartbeats

        beats = read_heartbeats(self.heartbeat_dir)
        report = self._health.check() if self._health is not None else None
        self.aggregator.update(beats, report)
        return self.aggregator.to_prometheus()

    def _healthz(self) -> tuple[bool, dict[str, Any]]:
        payload: dict[str, Any] = {
            "attempt": max(0, self.stats.attempts - 1),
            "world_size": self.stats.final_world_size,
            "completed": self.stats.completed,
        }
        if self._health is None:
            return True, payload
        report = self._health.check()
        payload.update(report.to_json())
        return report.healthy, payload

    def _statusz(self) -> dict[str, Any]:
        s = self.stats
        return {
            "attempts": s.attempts,
            "completed": s.completed,
            "world_sizes": list(s.world_sizes),
            "host_deaths": s.host_deaths,
            "hosts_quarantined": s.hosts_quarantined,
            "removed_hosts": [list(r) for r in s.removed_hosts],
            "events": [
                {"attempt": e.attempt, "kind": e.kind, "detail": e.detail}
                for e in list(s.events)[-50:]
            ],
        }

    def _poll_healthz(self) -> dict[str, Any] | None:
        """GET the consumed ``healthz_url``; returns its JSON body (from
        a 200 or a 503 — the 503 body carries the verdicts) or ``None``
        when unreachable/unparseable (warned once: the sidecar dying must
        never fail the fleet)."""
        import urllib.error
        import urllib.request

        try:
            try:
                resp = urllib.request.urlopen(
                    self.healthz_url, timeout=self.healthz_timeout
                )
                body, status = resp.read(), resp.status
            except urllib.error.HTTPError as e:
                body, status = e.read(), e.code
            import json

            out = dict(json.loads(body))
            out["status"] = int(status)
            return out
        except Exception as e:  # noqa: BLE001 - observation must not kill
            if not self._healthz_warned:
                self._healthz_warned = True
                self._event(
                    max(0, self.stats.attempts - 1),
                    "healthz-unreachable",
                    f"consumed healthz {self.healthz_url} failed "
                    f"({type(e).__name__}: {e}); continuing on heartbeat "
                    f"verdicts alone",
                )
            return None

    def _remote_verdicts(self) -> dict[int, str]:
        """Hosts the consumed ``/healthz`` names unhealthy, as
        ``{process_index: verdict kind}`` — empty when the endpoint is
        healthy, unarmed, or unreachable."""
        if self.healthz_url is None:
            return {}
        body = self._poll_healthz()
        if body is None or body.get("status") == 200:
            return {}
        out: dict[int, str] = {}
        for key, kind in (
            ("dead", "host-death"),
            ("wedged", "wedged"),
            ("slow", "straggler"),
        ):
            for host in body.get(key, ()) or ():
                try:
                    out.setdefault(int(host), kind)
                except (TypeError, ValueError):
                    continue
        return out

    # -- world planning ------------------------------------------------------
    def plan_relaunch(self, world: int, removed: set[int]) -> int:
        """Next world size after removing ``removed`` hosts from a
        ``world``-sized attempt.  At least one host is always charged (a
        stop with no identified culprit still shrinks by one — *something*
        broke the attempt, and relaunching at the same size against a
        hardware fault loops forever).  Raises :class:`FleetError` when
        the survivors fall below ``min_processes``."""
        next_world = world - max(1, len(removed))
        if next_world < self.min_processes:
            raise FleetError(
                f"fleet shrank below min_processes={self.min_processes}: "
                f"{world} host(s) minus {max(1, len(removed))} removed",
                self.stats,
            )
        return next_world

    def _specs(self, world: int, attempt: int, port: int) -> list[WorkerSpec]:
        coordinator = (
            f"{self.coordinator_host}:{port}" if world > 1 else ""
        )
        return [
            WorkerSpec(
                process_id=i,
                num_processes=world,
                coordinator=coordinator,
                attempt=attempt,
                heartbeat_dir=str(self.heartbeat_dir),
                checkpoint_dir=str(self.checkpoint_dir),
            )
            for i in range(world)
        ]

    # -- attempt lifecycle ---------------------------------------------------
    def _clear_heartbeats(self) -> None:
        """Drop the previous attempt's beats: a stale fresh-looking beat
        from a removed host must not feed this attempt's verdicts."""
        if self.heartbeat_dir.is_dir():
            for path in self.heartbeat_dir.glob("host_*.json"):
                try:
                    path.unlink()
                except OSError:  # pragma: no cover - racing cleaners
                    pass
        self.heartbeat_dir.mkdir(parents=True, exist_ok=True)

    def _launch(self, world: int, attempt: int) -> tuple[list[Any], list[WorkerSpec]]:
        port = free_coordinator_port(self.coordinator_host) if world > 1 else 0
        self._clear_heartbeats()
        specs = self._specs(world, attempt, port)
        workers = []
        for spec in specs:
            env = dict(self.env)
            env.update(spec.env())
            workers.append(self.spawn(self.command(spec), env, spec))
        self._event(
            attempt,
            "launch",
            f"{world} worker(s), coordinator "
            f"{specs[0].coordinator or '(none: single process)'}",
        )
        return workers, specs

    def _stop_attempt(
        self, workers: list[Any], attempt: int
    ) -> dict[int, int | None]:
        """SIGTERM every live worker, escalate to SIGKILL after the grace
        window, reap everything; returns {process_id: exit code}."""
        live = [i for i, w in enumerate(workers) if w.poll() is None]
        if live:
            self._event(
                attempt,
                "stop",
                f"terminating worker(s) {live} (grace "
                f"{self.grace_seconds:.1f}s, then SIGKILL)",
            )
        for i in live:
            workers[i].terminate()
        deadline = time.monotonic() + self.grace_seconds
        for i in live:
            remaining = max(0.0, deadline - time.monotonic())
            if workers[i].wait(remaining) is None:
                workers[i].kill()
                workers[i].wait(self.grace_seconds)
        codes = {i: w.poll() for i, w in enumerate(workers)}
        self.stats.exit_codes.append(codes)
        return codes

    def _watch(
        self, workers: list[Any], health: FleetHealth, attempt: int
    ) -> set[int] | None:
        """Watch one attempt until it completes (returns ``None``) or a
        verdict fails it (returns the hosts to remove — possibly empty:
        a whole-fleet stall with no identifiable culprit, which
        :meth:`plan_relaunch` charges one host for).  Raises
        :class:`FleetError` on the attempt timeout."""
        deadline = (
            time.monotonic() + self.attempt_timeout
            if self.attempt_timeout is not None
            else None
        )
        while True:
            codes = {i: w.poll() for i, w in enumerate(workers)}
            failed = {
                i
                for i, rc in codes.items()
                if rc is not None and rc not in (0, EX_PREEMPTED)
            }
            if failed:
                self.stats.host_deaths += len(failed)
                detail = ", ".join(
                    f"worker {i} rc={codes[i]}" for i in sorted(failed)
                )
                self._event(attempt, "host-death", detail)
                for i in sorted(failed):
                    self.stats.removed_hosts.append(
                        (attempt, i, f"exited rc={codes[i]}")
                    )
                return failed
            spontaneous_preempt = {
                i for i, rc in codes.items() if rc == EX_PREEMPTED
            }
            if spontaneous_preempt:
                # A worker stopped "gracefully" without being asked (an
                # injected SIGTERM, an external scheduler): resumable, but
                # this attempt cannot complete — restart at the SAME world
                # size minus nothing... except plan_relaunch always charges
                # one host; treat the preempted worker as the removal so
                # the accounting stays honest.
                detail = ", ".join(
                    f"worker {i} preempted (rc={EX_PREEMPTED})"
                    for i in sorted(spontaneous_preempt)
                )
                self._event(attempt, "host-death", detail)
                for i in sorted(spontaneous_preempt):
                    self.stats.removed_hosts.append(
                        (attempt, i, "preempted externally")
                    )
                return spontaneous_preempt
            if all(rc == 0 for rc in codes.values()):
                # The finally-side _stop_attempt records the exit codes.
                return None
            report = health.check()
            self.stats.last_report = report
            bad = set(report.unhealthy_hosts)
            # Exit-code truth beats heartbeat inference: a worker that
            # already exited 0 is complete, not dead, however stale its
            # final beat looks by now.
            bad -= {i for i, rc in codes.items() if rc == 0}
            # The consumed /healthz sidecar's verdicts merge in under the
            # same exit-code rule (hosts outside this attempt's world are
            # ignored — a stale sidecar must not remove a host twice).
            remote = {
                h: k
                for h, k in self._remote_verdicts().items()
                if 0 <= h < len(workers) and codes.get(h) != 0 and h not in bad
            }
            bad |= set(remote)
            live = {i for i, rc in codes.items() if rc is None}
            if (
                bad
                and live
                and set(report.wedged_hosts) >= live
                and not report.dead_hosts
                and not report.slow_hosts
            ):
                # EVERY live host reads as wedged: one stuck host stalls
                # all its peers' collectives, so a whole-fleet wedge
                # cannot name its culprit from the outside.  Stop the
                # fleet and shrink by one (plan_relaunch charges a host
                # for culprit-less stops); precise removal is reserved
                # for the verdicts that ARE per-host attributable (exit
                # codes, stale beats, self-reported deadline trips).
                self._event(
                    attempt,
                    "fleet-stall",
                    f"all {len(live)} live host(s) wedged "
                    f"({'; '.join(report.reasons[:2])}); culprit ambiguous "
                    f"— relaunching one host smaller",
                )
                self.stats.hosts_quarantined += 1
                return set()
            if bad:
                for i in sorted(bad):
                    v = report.verdicts.get(i)
                    if i in remote:
                        kind = remote[i]
                        reason = (
                            f"consumed healthz {self.healthz_url} named "
                            f"host {i} {kind}"
                        )
                    else:
                        reason = (
                            "; ".join(v.reasons)
                            if v is not None
                            else "unhealthy"
                        )
                        kind = (
                            "straggler"
                            if v is not None
                            and v.slow
                            and not (v.dead or v.wedged)
                            else (
                                "wedged"
                                if v is not None and v.wedged
                                else "host-death"
                            )
                        )
                    if kind == "straggler":
                        self.stats.hosts_quarantined += 1
                    elif kind == "wedged":
                        self.stats.hosts_quarantined += 1
                    else:
                        self.stats.host_deaths += 1
                    self._event(attempt, kind, reason)
                    self.stats.removed_hosts.append((attempt, i, reason))
                return bad
            if deadline is not None and time.monotonic() > deadline:
                # run()'s finally tears the workers down.
                raise FleetError(
                    f"attempt {attempt} exceeded its "
                    f"{self.attempt_timeout:.1f}s wall-clock budget with no "
                    f"verdict — treating the fleet as deadlocked",
                    self.stats,
                )
            time.sleep(self.poll_interval)

    # -- the supervisor loop -------------------------------------------------
    def run(self) -> FleetStats:
        """Drive the fleet to completion, shrinking on failures.

        Returns the :class:`FleetStats` of the successful run; raises
        :class:`FleetError` when the relaunch budget or ``min_processes``
        floor is hit (the stats ride on the exception)."""
        self.stats = FleetStats()
        self._metric_cursor = {}
        world = self.num_processes
        attempt = 0
        if self.endpoint is not None and not self.endpoint.started:
            self.endpoint.start()
            self._event(
                0,
                "endpoint",
                f"introspection serving at {self.endpoint.url} "
                f"(/metrics /healthz /statusz)",
            )
        try:
            while True:
                self.stats.attempts = attempt + 1
                self.stats.world_sizes.append(world)
                health = FleetHealth(
                    self.heartbeat_dir,
                    world,
                    dead_after=self.dead_after,
                    stall_after=self.stall_after,
                    eval_deadline=self.eval_deadline,
                    start_grace=self.start_grace,
                )
                # The endpoint's /healthz and /metrics staleness render
                # through the live attempt's verdict configuration.
                self._health = health
                workers, _specs = self._launch(world, attempt)
                try:
                    removed = self._watch(workers, health, attempt)
                finally:
                    # Whatever happened, never leak live workers past the
                    # attempt: completion leaves nothing to stop, every
                    # other path must tear the fleet down before
                    # relaunch/raise.
                    self._stop_attempt(workers, attempt)
                if removed is None:
                    self._event(
                        attempt, "complete", f"all {world} worker(s) exited 0"
                    )
                    self.stats.completed = True
                    # One final fold WITHOUT a staleness report: the
                    # workers exited 0, so their last beats are final
                    # totals to absorb, not dead hosts to mark — a
                    # post-run scrape of the supervisor registry then
                    # holds the fleet's complete counters.
                    from ..parallel.multihost import read_heartbeats

                    self.aggregator.update(
                        read_heartbeats(self.heartbeat_dir)
                    )
                    return self.stats
                next_world = self.plan_relaunch(world, removed)
                if attempt + 1 > self.max_relaunches:
                    raise FleetError(
                        f"relaunch budget of {self.max_relaunches} spent "
                        f"after attempt {attempt} removed host(s) "
                        f"{sorted(removed)}",
                        self.stats,
                    )
                self._event(
                    attempt,
                    "relaunch",
                    f"resuming on {next_world} surviving host(s) "
                    f"(was {world}; removed {sorted(removed)})",
                )
                world = next_world
                attempt += 1
        finally:
            if self.endpoint is not None:
                self.endpoint.stop()
