"""Checkpointed, retrying run supervisor for long workflow executions.

``StdWorkflow.run`` compiles N generations into one ``lax.fori_loop`` — the
fastest shape for healthy hardware, and the most fragile for a multi-hour
run: a backend loss anywhere inside the loop discards everything.  The
BASELINE.md outage record shows both observed failure signatures this module
is built against:

* **hard loss** — the tunnel relay dies and every dispatch raises
  ``XlaRuntimeError: UNAVAILABLE`` (or ``INTERNAL``);
* **silent hang** — probes block ~25 minutes inside backend init before
  failing; a bare ``block_until_ready`` would wedge the driver for as long.

:class:`ResilientRunner` trades a sliver of dispatch overhead for
survivability: generations run as **fused jitted segments** — each chunk is
ONE compiled ``lax.scan`` over generations whose body carries every
per-generation resilience feature (non-finite quarantine, monitor counters,
captured-and-batched history sinks, optional unhealthy-state early stop),
so the host touches the device exactly once per segment — and between
segments the supervisor — plain Python, outside XLA — flushes the batched
telemetry, probes health, checkpoints atomically, enforces a watchdog
deadline, retries with exponential backoff, and can fall back to CPU to
limp a run to its next checkpoint.  ``fused=False`` keeps the per-
generation ``fori_loop`` shape (in-loop monitor callbacks) as a debug
fallback.

The checkpoint layout under ``checkpoint_dir`` is flat::

    ckpt_00000010.npz          # state after 10 completed generations
    ckpt_00000020.npz          # manifest records generation, versions
    ckpt_00000030.npz.corrupt  # quarantined: failed digest verification

Resume scans newest-first (:func:`scan_checkpoints`): files whose *bytes*
are damaged (torn write, bit flip — digest verification catches what zip
CRCs do not) are **quarantined** — renamed ``*.corrupt``, never deleted, so
post-mortems keep their evidence — and each skip is recorded as a
structured :class:`CheckpointSkip` in ``RunStats``; the first remaining
candidate that validates against the template state wins.  One bad file
cannot lose the run.

Checkpoint writes are **asynchronous by default**: serialization and the
durable atomic publish happen on a background thread
(:class:`~evox_tpu.utils.AsyncCheckpointWriter`) with at most one write in
flight, so the device loop never blocks on disk; stale-checkpoint GC runs
only after the successor is durably published, so the newest surviving
checkpoint is always intact.  ``SIGTERM``/``SIGINT`` (scheduler preemption)
is handled cooperatively via :class:`~evox_tpu.resilience.PreemptionGuard`
— see ``preemption.py``.
"""

from __future__ import annotations

import contextlib
import re
import threading
import time
import warnings
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, NamedTuple, Union

import jax

from ..core import State, Workflow
from ..obs.plane import Observability, resolve_obs
from ..utils.checkpoint import (
    AsyncCheckpointWriter,
    CheckpointCorruptError,
    CheckpointError,
    CheckpointStore,
    load_state,
    read_manifest,
    save_state,
    verify_checkpoint,
)
from .elastic import (
    check_topology,
    remesh_state,
    topology_differs,
    workflow_mesh,
    workflow_topology,
)
from .health import HealthProbe, HealthReport
from .preemption import Preempted, PreemptionGuard
from .restart import RestartContext, RestartEvent, RestartPolicy

__all__ = [
    "ResilientRunner",
    "RetryPolicy",
    "RunStats",
    "SegmentTiming",
    "CheckpointSkip",
    "ResilienceError",
    "WatchdogTimeout",
    "default_retryable",
    "latest_checkpoint",
    "scan_checkpoints",
]

_CKPT_RE = re.compile(r"ckpt_(\d+)\.npz$")


class WatchdogTimeout(RuntimeError):
    """A segment exceeded the runner's watchdog deadline (the silent-hang
    outage signature: dispatch blocks in backend init instead of failing)."""


class ResilienceError(RuntimeError):
    """A segment kept failing after the full retry budget (and CPU fallback,
    if enabled) was exhausted.  ``__cause__`` carries the last failure."""


# Substrings of the gRPC/XLA status messages that indicate the *backend* —
# not the program — failed, and a retry against a recovered backend can
# succeed.  "INTERNAL" is included because host-callback failures and
# backend-loss both surface as INTERNAL XlaRuntimeErrors on some paths
# (BASELINE.md round-4/5 logs show both UNAVAILABLE and INTERNAL from the
# same outage).
RETRYABLE_SIGNATURES = (
    "UNAVAILABLE",
    "INTERNAL",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "DATA_LOSS",
    "Connection refused",
    "Connection reset",
    "Socket closed",
    "failed to connect",
)

# Marker an error message can carry to opt out of retries even when the
# surrounding transport noise matches a retryable signature (used by
# fault-injection to simulate genuinely fatal crashes; XLA wraps every host
# callback failure in an "INTERNAL: CpuCallback error" envelope, so the
# inner error must be able to overrule the envelope).
NONRETRYABLE_MARKER = "NONRETRYABLE"

_XlaRuntimeError: type[BaseException]
try:  # jax >= 0.4.14 exposes the alias; fall back to the jaxlib type.
    _XlaRuntimeError = jax.errors.JaxRuntimeError  # type: ignore[attr-defined]
except AttributeError:  # pragma: no cover - very old jax
    from jaxlib.xla_extension import XlaRuntimeError as _XlaRuntimeError


def default_retryable(exc: BaseException) -> bool:
    """Is this failure worth retrying against a (possibly recovered) backend?

    * :class:`WatchdogTimeout` — always (it is the hang signature).
    * Errors whose message carries ``NONRETRYABLE`` — never.
    * ``XlaRuntimeError`` / ``RuntimeError`` whose message matches a known
      backend-loss signature (``UNAVAILABLE``, ``INTERNAL``, ...) — yes.
    * Everything else (shape errors, user exceptions, ...) — no: retrying a
      deterministic program bug burns the budget without hope.
    """
    if isinstance(exc, WatchdogTimeout):
        return True
    msg = str(exc)
    if NONRETRYABLE_MARKER in msg:
        return False
    if isinstance(exc, (_XlaRuntimeError, RuntimeError)):
        return any(sig in msg for sig in RETRYABLE_SIGNATURES)
    return False


@dataclass
class RetryPolicy:
    """Exponential-backoff retry budget for one segment.

    ``max_retries`` counts *retries* (the first attempt is free); the delay
    before retry ``k`` (1-based) is ``backoff_base * backoff_factor**(k-1)``
    capped at ``backoff_max`` seconds.
    """

    max_retries: int = 3
    backoff_base: float = 1.0
    backoff_factor: float = 2.0
    backoff_max: float = 300.0
    retryable: Callable[[BaseException], bool] = default_retryable

    def delay(self, retry_index: int) -> float:
        """Backoff delay before 1-based retry ``retry_index``."""
        return min(
            self.backoff_base * self.backoff_factor ** (retry_index - 1),
            self.backoff_max,
        )


@dataclass
class CheckpointSkip:
    """Structured record of one resume candidate the scan rejected.

    ``quarantined=True`` means the file's bytes were damaged (digest /
    archive verification failed) and it was renamed ``*.corrupt`` —
    preserved for post-mortems, excluded from every future scan.
    ``quarantined=False`` means a well-formed checkpoint merely failed
    validation against this run's template (different config, unusable
    lineage) and was left in place."""

    path: str
    reason: str
    quarantined: bool = False


class SegmentTiming(NamedTuple):
    """Where one segment's wall clock went, measured at the boundary.

    ``compile_seconds`` is the AOT compile paid for this segment's
    program (0.0 once the executable is cached — only the first segment
    of each distinct chunk length compiles); ``execute_seconds`` is
    dispatch + ``block_until_ready``; ``checkpoint_block_seconds`` is how
    long the loop was blocked publishing this boundary's checkpoint
    (submit + predecessor barrier under the async writer).  On a retried
    segment the numbers are the *successful* attempt's."""

    generation: int
    compile_seconds: float
    execute_seconds: float
    checkpoint_block_seconds: float


@dataclass
class RunStats:
    """Observable record of what the supervisor did during :meth:`run`.

    ``restarts`` is the run's full restart lineage — on resume it is
    restored from the checkpoint manifest, so events fired before a kill
    stay visible.  ``last_report`` is the most recent
    :class:`~evox_tpu.resilience.HealthReport` (``None`` when the runner
    has no health probe).  ``checkpoint_block_seconds`` is the wall-clock
    the *generation loop* spent blocked on checkpointing — submit +
    barrier time under the async writer, full serialize-and-publish time
    without it (the number ``tools/bench_checkpoint_overhead.py``
    compares)."""

    resumed_from_generation: int | None = None
    completed_generations: int = 0
    segments_run: int = 0
    retries: int = 0
    watchdog_timeouts: int = 0
    cpu_fallbacks: int = 0
    checkpoints_written: int = 0
    failures: list[str] = field(default_factory=list)
    health_checks: int = 0
    unhealthy_probes: int = 0
    restarts: list[RestartEvent] = field(default_factory=list)
    last_report: HealthReport | None = None
    preempted: bool = False
    preemption_reason: str | None = None
    resumed_after_preemption: bool = False
    checkpoint_skips: list[CheckpointSkip] = field(default_factory=list)
    checkpoint_write_failures: int = 0
    checkpoint_block_seconds: float = 0.0
    chunk_sizes: list[int] = field(default_factory=list)
    # Fused segments whose in-scan early stop froze a poisoned state before
    # the scheduled boundary (``fused_early_stop``); the skipped
    # generations were lax.cond no-ops, and the boundary probe saw the
    # frozen state.
    early_stops: int = 0
    # One SegmentTiming per executed segment (init segment included):
    # where the wall clock went — compile vs execute vs checkpoint block.
    segment_timings: list[SegmentTiming] = field(default_factory=list)


def _numbered_checkpoints(
    checkpoint_dir: Union[str, Path]
) -> list[tuple[int, Path]]:
    """All ``ckpt_<generation>.npz`` files in the directory, sorted by
    generation ascending.  Stray non-numbered files are ignored."""
    out = []
    for path in Path(checkpoint_dir).glob("ckpt_*.npz"):
        m = _CKPT_RE.search(path.name)
        if m:
            out.append((int(m.group(1)), path))
    return sorted(out)


# One shared never-overwrite-evidence quarantine naming rule (also used
# by the executable cache and the request journal).
from ..utils.checkpoint import quarantine_target as _quarantine_target  # noqa: E402,E501


def scan_checkpoints(
    checkpoint_dir: Union[str, Path],
    *,
    verify: Union[bool, str] = False,
    quarantine: bool = False,
    store: CheckpointStore | None = None,
) -> tuple[list[tuple[int, Path]], list[tuple[Path, str, bool]]]:
    """Enumerate a checkpoint directory into ``(valid, rejected)``.

    ``valid`` is ``[(generation, path)]`` ascending — the candidates a
    resume should probe newest-first.  ``rejected`` is
    ``[(path, reason, quarantined)]`` for every numbered file excluded:
    byte-damaged archives (:class:`~evox_tpu.utils.CheckpointCorruptError`
    from :func:`~evox_tpu.utils.verify_checkpoint` — torn writes, bit
    flips) and, with ``verify=True``, archives without a usable manifest.

    With ``quarantine=True``, *corrupt* files are additionally renamed
    ``<name>.corrupt`` (``.corrupt.N`` when earlier evidence already holds
    the name) — out of every future scan's way, but never deleted
    (evidence beats hygiene when a disk is eating checkpoints); the
    reject's ``quarantined`` flag reports whether the rename actually
    happened (a failed rename leaves it ``False`` and the file in place).
    Non-corrupt rejects are never renamed: a well-formed checkpoint that
    merely fails verification policy may still be valid for someone else.

    ``verify=False`` trusts the directory listing (no file is opened) —
    the cheap mode :func:`latest_checkpoint` uses by default.
    ``verify=True`` (or ``"full"``) reads and digests **every** candidate
    up front — a deliberate trade: the directory (bounded by
    ``keep_checkpoints`` files under the runner) is fully triaged in one
    pass, so corrupt files are quarantined even when a newer candidate
    wins.  ``verify="manifest"`` is the **fast path** for large
    directories (the multi-tenant service's per-tenant namespaces hold
    hundreds of archives, and a full pass is O(N·bytes) of SHA-256 per
    scan): each candidate's manifest digest and entry inventory are
    checked — truncation and manifest damage still reject (and
    quarantine) exactly as before — but leaf digests are NOT recomputed;
    the caller fully verifies only the archive it actually selects
    (``load_state(verify=True)``, which the runner does under
    ``verify_resume="manifest"``).  Template validation (shape/dtype
    against a live run's state) is *not* this function's job; that
    happens at ``load_state`` time in :meth:`ResilientRunner.resume`.
    Renames route through ``store`` (default local), the same
    :class:`~evox_tpu.utils.CheckpointStore` seam every other checkpoint
    file operation uses.
    """
    if verify not in (False, True, "full", "manifest"):
        raise ValueError(
            f"verify must be False, True, 'full', or 'manifest', got "
            f"{verify!r}"
        )
    store = store if store is not None else CheckpointStore()
    valid: list[tuple[int, Path]] = []
    rejected: list[tuple[Path, str, bool]] = []
    for gen, path in _numbered_checkpoints(checkpoint_dir):
        if verify:
            try:
                # Positional-compatible call in full mode (test doubles and
                # wrappers of verify_checkpoint predate the leaves kwarg).
                if verify == "manifest":
                    verify_checkpoint(path, leaves=False)
                else:
                    verify_checkpoint(path)
            except FileNotFoundError:
                # The file vanished between the listing and the read: a
                # concurrent cleaner (the fleet's primary process GC-ing or
                # quarantining while a read-only peer scans) got there
                # first.  Not this scanner's candidate, not this scanner's
                # problem.
                rejected.append(
                    (path, "vanished during scan (concurrent cleaner)", False)
                )
                continue
            except CheckpointCorruptError as e:
                renamed = False
                if quarantine:
                    try:
                        store.rename(path, _quarantine_target(path))
                        renamed = True
                    except OSError:  # racing cleaners / read-only store
                        pass
                rejected.append((path, str(e), renamed))
                continue
            except CheckpointError as e:
                rejected.append((path, str(e), False))
                continue
        valid.append((gen, path))
    return valid, rejected


def latest_checkpoint(
    checkpoint_dir: Union[str, Path], *, verify: bool = False
) -> Path | None:
    """Newest checkpoint file (by generation number) in ``checkpoint_dir``,
    or ``None``.

    By default this is a pure directory-listing lookup: **validity is NOT
    checked**, so the returned file may still be refused by ``load_state``
    — resume logic must keep probing (exactly what
    :meth:`ResilientRunner.resume` does via :func:`scan_checkpoints`).
    Pass ``verify=True`` to skip past archives that fail digest
    verification (nothing is renamed; see :func:`scan_checkpoints` for the
    quarantining variant)."""
    valid, _ = scan_checkpoints(checkpoint_dir, verify=verify)
    return valid[-1][1] if valid else None


class ResilientRunner:
    """Supervises a workflow run: chunked jitted segments + atomic
    checkpoints + auto-resume + retry/backoff + watchdog + CPU fallback.

    Usage::

        wf = StdWorkflow(PSO(10_000, lb, ub), Ackley(), monitor=EvalMonitor())
        runner = ResilientRunner(wf, "ckpts/run1", checkpoint_every=50)
        state = runner.run(wf.init(jax.random.key(0)), n_steps=5_000)
        # ... process dies at generation 3_217; rerun the same two lines:
        # the runner resumes from ckpt_00003200.npz instead of restarting.

    Determinism: a resumed (or retried) run is bit-identical to an
    uninterrupted run of the same runner configuration — PRNG keys live in
    the checkpointed state, and resume always lands on a segment boundary,
    so the remaining compiled programs are exactly the ones the
    uninterrupted run would have executed (tested in
    ``tests/test_resilience.py``).  Against the single-program
    ``workflow.run(state, n)`` the trajectory may drift by float
    reassociation across segment boundaries, exactly like different
    ``unroll`` factors; the supervisor trades that ulp-level equivalence
    for survivability.

    Monitor caveat: on the **fused** path (the default) a multi-generation
    segment's history is captured in-program and flushed only after the
    segment *succeeds*, so retrying such a segment never duplicates
    history entries.  The exception is a single-generation segment (a
    run's ragged tail, or a wall-interval-adapted chunk of 1), which runs
    as the plain step with its per-generation callback live — a retry
    after that callback fired replays it, exactly like the per-generation
    debug path (``fused=False``), where retries replay the failed chunk
    with all in-loop callbacks live and the host-side history may contain
    repeated generation entries after a recovery.  In-state metrics —
    top-k, ``num_nonfinite`` — are part of the checkpoint and stay
    consistent in every case, and history entries carry generation tags
    for dedup; see ``docs/guide/resilience.md``.
    """

    def __init__(
        self,
        workflow: Workflow,
        checkpoint_dir: Union[str, Path],
        *,
        checkpoint_every: int = 10,
        retry: RetryPolicy | None = None,
        watchdog_timeout: float | None = None,
        compile_timeout: float | None = None,
        cpu_fallback: bool = False,
        keep_checkpoints: int = 3,
        on_event: Callable[[str], None] | None = None,
        health: HealthProbe | None = None,
        restart: RestartPolicy | None = None,
        max_restarts: int = 5,
        remesh: bool = True,
        async_checkpoints: bool = True,
        checkpoint_wall_interval: float | None = None,
        preemption: Union[PreemptionGuard, bool, None] = None,
        store: CheckpointStore | None = None,
        exec_cache: Any | None = None,
        verify_resume: Union[bool, str] = True,
        fused: bool = True,
        fused_early_stop: bool = False,
        primary: bool | None = None,
        heartbeat: Any | None = None,
        obs: Union[Observability, bool, None] = None,
        controller: Any | None = None,
    ):
        """
        :param workflow: any ``Workflow`` whose ``init_step``/``step`` are
            jittable pure ``state -> state`` functions (``StdWorkflow`` is).
        :param checkpoint_dir: directory for ``ckpt_<generation>.npz`` files
            (created if absent).  Point a resumed run at the same directory.
        :param checkpoint_every: generations per segment; each segment is one
            compiled ``fori_loop`` program and one checkpoint.  Smaller =
            less lost work per failure, more dispatch + checkpoint overhead.
        :param retry: backoff budget per segment (:class:`RetryPolicy`).
        :param watchdog_timeout: seconds a segment's *execution* (dispatch +
            ``block_until_ready``) may take before it is abandoned and
            treated as a retryable failure — set this to catch the
            silent-hang outage signature.  ``None`` disables the watchdog.
            Compilation is excluded: segments are AOT-compiled (and cached)
            before the deadline starts, so a cold multi-minute XLA compile
            on a healthy backend cannot trip a deadline sized for execution.
        :param compile_timeout: optional separate deadline (seconds) for the
            AOT compile of a segment — compiles also block forever on a hung
            backend (the BASELINE.md probes hung in backend *init*), so a
            long-running service should set this to its tolerance for
            compile latency.  ``None`` (default) leaves compiles unguarded.
        :param cpu_fallback: after the retry budget is exhausted, re-run the
            segment on the host CPU backend (fresh retry budget) so the run
            limps to its next checkpoint instead of dying — the in-process
            equivalent of restarting under ``JAX_PLATFORMS=cpu``, without
            losing the supervisor (state is ``device_put`` to the CPU
            backend and programs re-lowered under ``jax.default_device``).
        :param keep_checkpoints: how many newest checkpoints to retain
            (older ones are pruned after each successful write); ``0`` keeps
            everything.
        :param on_event: optional callback receiving one human-readable line
            per supervisor event (resume/retry/fallback/checkpoint) —
            defaults to ``warnings.warn`` for failures and silence for
            routine events.  With ``async_checkpoints=True`` (the
            default), checkpoint-publish and write-failure events arrive
            on the background writer thread, possibly interleaved with
            main-loop events — a callback that mutates shared state must
            be thread-safe.
        :param health: optional :class:`~evox_tpu.resilience.HealthProbe`
            run on the state at every chunk boundary (after the segment,
            before the next one) — detects degenerate searches (non-finite
            state, diversity collapse, step-size blow-up, stagnation) that
            never raise.  Reports land in ``stats.last_report``.
        :param restart: optional
            :class:`~evox_tpu.resilience.RestartPolicy` applied when the
            probe returns an unhealthy verdict (requires ``health``);
            ``None`` downgrades unhealthy verdicts to warnings.  Fired
            restarts are recorded in ``stats.restarts`` and in every later
            checkpoint's manifest, so a resumed run replays them
            bit-identically.
        :param max_restarts: restart budget per :meth:`run`; once spent,
            further unhealthy verdicts warn but the run continues (an
            unhealthy run that finishes is still better than an aborted
            one).
        :param remesh: allow resuming a checkpoint written under a
            *different* mesh topology (elastic resume: a run checkpointed
            on an 8-device ``pop`` mesh continues on 4 — or 2, or 1 —
            after a pod reschedule).  The state is repartitioned for the
            current mesh and the trajectory stays bit-identical, because
            checkpointed state is global and per-individual PRNG streams
            fold the global slot index (``resilience/elastic.py``).
            ``False`` makes a topology change a loud, structured
            :class:`~evox_tpu.utils.CheckpointError` instead.
        :param async_checkpoints: write checkpoints on a background thread
            (:class:`~evox_tpu.utils.AsyncCheckpointWriter`, at most one
            write in flight) so the generation loop never blocks on
            serialization or disk — segment N+1 computes while segment N's
            checkpoint publishes.  Write failures (disk full, injected
            chaos) are reported as warnings and counted in
            ``stats.checkpoint_write_failures``; the previous checkpoint
            stays the resume point, and GC runs only after a successful
            durable publish so the newest surviving checkpoint is always
            intact.  ``run()`` barriers the writer before returning (and on
            any exit), so the final state is durably on disk by the time
            control returns.  ``False`` restores the synchronous write on
            the loop (``tools/bench_checkpoint_overhead.py`` measures the
            difference).
        :param checkpoint_wall_interval: target *seconds* between
            checkpoints.  When set, the runner measures segment wall-clock
            and adapts the chunk length (1 up to ``checkpoint_every``,
            quantized to powers of two so at most log2 distinct segment
            programs compile) toward this cadence — bounding preemption
            loss in seconds of work rather than generations, which is the
            quantity a scheduler's grace window is denominated in.  Note
            the segment boundaries then depend on measured timing, so the
            fixed-boundary guarantee behind bit-identical *comparisons*
            between separately-chunked runs no longer applies (resume of
            an interrupted run is still exact: it continues from a
            checkpointed boundary).
        :param preemption: a
            :class:`~evox_tpu.resilience.PreemptionGuard` (or ``True`` for
            a default one) that converts SIGTERM/SIGINT and provider
            maintenance events into a graceful stop: at the next segment
            boundary the runner barriers any in-flight checkpoint write,
            publishes an emergency checkpoint whose manifest records
            ``preempted``, restores prior signal handlers, and raises
            :class:`~evox_tpu.resilience.Preempted` — rerunning the same
            supervisor resumes bit-identically.  The runner installs the
            guard for the duration of :meth:`run` if the caller has not
            already installed it.
        :param store: the :class:`~evox_tpu.utils.CheckpointStore` all
            checkpoint file operations route through — inject storage
            chaos with :class:`~evox_tpu.resilience.FaultyStore`.
        :param verify_resume: digest-verify checkpoints during the resume
            scan (:func:`scan_checkpoints`): byte-damaged files (torn
            writes, bit flips) are quarantined as ``*.corrupt`` and
            reported as structured ``stats.checkpoint_skips`` instead of
            being silently loaded or crashing the scan.  ``True`` (the
            default) recomputes every candidate's leaf digests up front;
            ``"manifest"`` triages candidates by manifest digest and
            entry inventory only — O(manifest) per candidate instead of
            O(archive bytes) — and fully verifies just the checkpoint
            actually selected, at load time (quarantine semantics are
            unchanged: damage found either way still renames the file
            aside and falls back).  The fast mode is built for
            directories holding hundreds of archives (per-tenant service
            namespaces); ``False`` disables scan verification entirely.
        :param fused: compile each checkpoint segment as ONE
            ``lax.scan`` over generations with the resilience features
            carried *inside* the program
            (:meth:`StdWorkflow.run_segment <evox_tpu.workflows.StdWorkflow.run_segment>`):
            quarantine and monitor counters stay in-step as always, the
            monitor's host-side history sinks are captured into batched
            telemetry instead of firing one ``io_callback`` per
            generation, and per-generation best fitness rides out with
            the segment — so the segment itself costs the host one
            ``device_get`` instead of one round-trip per generation (the
            boundary health probe, when configured, still runs its own
            standalone scan: one program shared with the debug path and
            the post-restart/resume probes, keeping every verdict
            bit-identical across paths).  This is the default hot path; the
            final state is bit-identical to the per-generation path.
            ``False`` (or a workflow without ``_segment_program``) falls
            back to the per-generation ``fori_loop`` debug path, whose
            in-loop monitor callbacks make each generation individually
            observable from the host.
        :param fused_early_stop: with ``fused``, additionally carry the
            health probe's hard detectors (non-finite state, diversity
            floor, step-size range, dead/collapsed shards) in-scan and
            freeze the state the moment a generation turns unhealthy —
            the remaining generations of the segment become
            ``lax.cond``-guarded no-ops, so a poisoned state stops
            evolving mid-segment instead of compounding until the
            boundary (detection/restart latency is still the segment
            boundary).  Off by default because the in-scan predicate
            shifts XLA fusion by ulps: an early-stop run is exactly
            reproducible against itself, but not bit-identical to a
            ``fused=False`` (or early-stop-off) run of the same
            configuration.
        :param primary: whether this process holds the fleet's
            **single-writer** role for the checkpoint directory.  Defaults
            to ``evox_tpu.parallel.is_primary()`` — ``True`` for every
            single-process run, and for process 0 of a
            ``jax.distributed`` fleet.  A non-primary runner computes the
            identical trajectory (checkpoint *decisions* are replicated)
            but performs **no mutating directory operation**: no publish,
            no GC, no ``*.corrupt`` quarantine rename — its store is
            swapped for a
            :class:`~evox_tpu.utils.ReadOnlyCheckpointStore`, so even a
            code path that slips past the gating is refused at the seam.
            Resume still *reads* the primary's checkpoints on every
            process.
        :param heartbeat: optional
            :class:`~evox_tpu.parallel.HostHeartbeat` (or any object with
            a compatible ``beat``) the runner publishes progress through:
            one beat per segment boundary carrying the completed
            generation and the segment's execution seconds — the signal a
            :class:`~evox_tpu.resilience.FleetSupervisor` renders into
            per-host dead/wedged/slow verdicts.
        :param obs: the :class:`~evox_tpu.obs.Observability` plane this
            runner publishes through — structured events for every
            supervisor decision (the string ``on_event`` callback keeps
            working unchanged alongside), ``evox_runner_*`` metrics into
            the plane's registry at every segment boundary, and (when the
            plane carries a :class:`~evox_tpu.obs.Tracer`) host-side
            spans per boundary phase plus an opt-in
            ``jax.profiler.trace`` window around the Nth segment.
            A plane carrying a :class:`~evox_tpu.obs.FlightRecorder`
            additionally switches on the per-generation flight
            telemetry in the fused segments (ring-fed at every
            telemetry flush; postmortem bundles dump on restart /
            early-stop / preemption / quarantine-storm events), and
            every AOT compile publishes its XLA cost/memory verdict
            (``evox_segment_*`` gauges) with live device-memory,
            throughput, and roofline gauges at segment boundaries.
            ``None`` (default) builds a plane on the process-local
            default registry with an in-memory event ring; ``False``
            disables instrumentation entirely.  All instrumentation is
            strictly host-side at segment boundaries — the flight
            signals are pure scan outputs — and the evolving state is
            identical with and without it (``tests/test_obs.py`` and
            ``tests/test_flight.py`` pin bit-identity,
            ``tools/bench_obs_overhead.py`` gates the wall-clock cost
            with the flight recorder on).
        :param controller: optional
            :class:`~evox_tpu.control.Controller` closing the
            observe→decide→act loop over this runner.  Two planes, each
            opt-in on the controller: **trend verdicts** — at every
            boundary where the threshold probe reads healthy, the
            controller examines the flight recorder's signal window
            (slope/EMA, NaN-robust) and may declare the run degenerate
            *early* (fitness-slope stagnation, diversity-collapse
            trajectory, quarantine-storm prediction), firing the same
            ``restart=`` policy the probe would — needs the obs plane's
            flight recorder; with it detached the controller degrades
            to the threshold probes with one structured warning and the
            run completes.  **Self-tuning cadence** — the next segment's
            scan length is sized from measured compile/execute ratios
            and checkpoint-block seconds (``stats.segment_timings``),
            generalizing (and taking precedence over)
            ``checkpoint_wall_interval``.  Every decision carries its
            evidence, is appended to the controller's journal when one
            is wired, and is *excluded from bit-identity* like
            ``num_preemptions``: a controller that fires no decision
            leaves the run bit-identical to ``controller=None``
            (``tests/test_control.py``).  The controller never crashes
            a run — every consult is exception-guarded on both sides.
        """
        if checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every}"
            )
        if keep_checkpoints < 0:
            raise ValueError(
                f"keep_checkpoints must be >= 0, got {keep_checkpoints}"
            )
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        if restart is not None and health is None:
            raise ValueError(
                "a restart policy needs a health probe to trigger it; pass "
                "health=HealthProbe(...) alongside restart="
                f"{type(restart).__name__}(...)"
            )
        self.workflow = workflow
        self.checkpoint_dir = Path(checkpoint_dir)
        self.checkpoint_every = int(checkpoint_every)
        self.retry = retry if retry is not None else RetryPolicy()
        self.watchdog_timeout = watchdog_timeout
        self.compile_timeout = compile_timeout
        self.cpu_fallback = cpu_fallback
        self.keep_checkpoints = int(keep_checkpoints)
        self.on_event = on_event
        if (
            checkpoint_wall_interval is not None
            and checkpoint_wall_interval <= 0
        ):
            raise ValueError(
                f"checkpoint_wall_interval must be > 0 seconds, got "
                f"{checkpoint_wall_interval}"
            )
        self.health = health
        self.restart = restart
        self.max_restarts = int(max_restarts)
        self.remesh = bool(remesh)
        if primary is None:
            # One definition of the single-writer role (multi-host fleets):
            # process 0 writes, everyone else is read-only.
            from ..parallel.multihost import is_primary

            primary = is_primary()
        self.primary = bool(primary)
        if self.primary:
            self.store = store if store is not None else CheckpointStore()
        else:
            # Belt and braces under the CheckpointStore seam: even a code
            # path that slips past the primary gating below cannot mutate
            # the directory from a non-primary process.
            from ..utils.checkpoint import ReadOnlyCheckpointStore

            self.store = ReadOnlyCheckpointStore()
        self.heartbeat = heartbeat
        # Persistent AOT executable cache (utils.ExecutableCache): segment
        # programs survive the process, so a restarted run resumes without
        # paying the cold XLA compile (the serving daemon's zero-cold-start
        # plane, available to solo runners too).  Saves/loads are
        # digest-guarded and failure-isolated inside the cache itself.
        self.exec_cache = exec_cache or None
        self.obs = resolve_obs(obs, run_id=Path(checkpoint_dir).name)
        # The closed-loop control plane (evox_tpu/control): trend verdicts
        # from the flight window + self-tuned cadence from measured
        # timings.  Bound to this runner's obs plane so its decisions and
        # degrade warnings publish as "control" events.
        self.controller = controller
        if controller is not None:
            controller.bind(self.obs)
        self._controller_chunk = self.checkpoint_every
        # Counters are monotone and (by default) process-shared: publish
        # per-run stats as deltas against this cursor, reset with stats.
        self._metric_cursor: dict[str, float] = {}
        if verify_resume not in (False, True, "full", "manifest"):
            raise ValueError(
                f"verify_resume must be False, True, 'full', or "
                f"'manifest', got {verify_resume!r}"
            )
        self.verify_resume = verify_resume
        self.checkpoint_wall_interval = checkpoint_wall_interval
        # ``preemption=True`` builds a guard the runner OWNS: each run()
        # resets it, so rerunning the same runner after a Preempted raise
        # resumes instead of instantly re-tripping on the stale flag.  A
        # caller-provided guard belongs to the caller (a pre-tripped flag
        # may be intentional); the caller resets it between runs.
        self._owns_guard = preemption is True
        self.preemption: PreemptionGuard | None = (
            PreemptionGuard() if preemption is True else (preemption or None)
        )
        self._writer: AsyncCheckpointWriter | None = (
            AsyncCheckpointWriter(
                store=self.store,
                durable=True,
                on_error=self._note_write_failure,
                registry=self.obs.registry if self.obs is not None else None,
            )
            if async_checkpoints and self.primary
            else None
        )
        # Fused segments need the workflow to expose the segment builder
        # (StdWorkflow does); any other workflow silently keeps the
        # per-generation fori_loop shape.
        self.fused = bool(fused) and hasattr(workflow, "_segment_program")
        self.fused_early_stop = bool(fused_early_stop)
        self._segment_cfg = None
        self._adaptive_chunk = 1
        self._per_gen_ema: float | None = None
        self._last_exec_seconds = 0.0
        self._last_compile_seconds = 0.0
        self.stats = RunStats()
        self._forced_cpu = False
        # Restart policies may swap ``workflow.algorithm`` (population
        # regrows); remember the base configuration so every run() starts
        # from it and resume can replay the recorded lineage on top.
        self._base_algorithm = getattr(workflow, "algorithm", None)
        # Manifest flag of the checkpoint a resume landed on: True when the
        # boundary was already probed before the write (post-restart
        # checkpoints), so the resumed run must not probe it again.
        self._resumed_probed = False
        self._rebind_workflow()

    def _rebind_workflow(self) -> None:
        """(Re-)derive jit-traced programs and drop AOT executables — called
        at construction and whenever a restart policy mutates the workflow
        (a stale trace would silently run the OLD algorithm)."""
        # One compiled program per distinct chunk length (at most two: the
        # steady chunk and the final ragged one).
        self._jit_init_step = jax.jit(self.workflow.init_step)
        self._jit_segment = jax.jit(self._segment, static_argnums=1)
        # AOT-compiled executables keyed by (program, chunk, backend, state
        # signature): compiled OUTSIDE the watchdog so cold-compile latency
        # never counts against the execution deadline.
        self._exec_cache: dict = {}
        # Persistent-cache identity salt (workflow static-config digest):
        # recomputed lazily after every rebind, since a restart policy
        # swapping the algorithm changes the compiled program.
        self._exec_cache_identity: str | None = None
        # XLA's cost/memory verdict per compiled program shape, keyed by
        # (which, chunk): captured at AOT-compile time (obs/xla.py),
        # consumed at segment boundaries for the in-process roofline.
        self._program_analysis: dict = {}

    # -- program shapes ----------------------------------------------------
    def _fused_cfg(self):
        """The fused segment's static config: the health probe's detector
        set (which drives the in-scan early-stop predicate) plus the
        runner's early-stop choice.  ``metrics=False``: the boundary
        verdict comes from the probe's OWN standalone scan of the boundary
        state — the same program for fused and debug segments, and for the
        post-restart/post-resume probes that have no telemetry to read —
        so an end-of-segment snapshot inside the fused program would be
        computed and transferred every segment only to be discarded
        (in-program metric values could also drift by ulps from the
        standalone scan's, which would let the two paths' verdicts and
        persisted stagnation windows diverge at a threshold margin).
        Standalone ``run_segment`` callers keep ``metrics=True`` as their
        default.  Cached — the config must compare equal across calls or
        every segment would retrace."""
        if self._segment_cfg is None:
            self._segment_cfg = self.workflow.segment_config(
                health=self.health,
                metrics=False,
                stop_on_unhealthy=self.fused_early_stop,
                # A FlightRecorder on the obs plane switches on the
                # per-generation flight telemetry: extra scan outputs,
                # zero host callbacks, carry untouched (bit-identity is
                # pinned in tests/test_flight.py).
                flight=(
                    self.obs is not None and self.obs.flight is not None
                ),
            )
        return self._segment_cfg

    def _segment(self, state: State, n: int):
        if n == 1:
            # A single-generation segment (the ragged tail of a run) gains
            # nothing from fusion — and sharing ONE plain step program
            # between the fused and debug paths is what keeps them
            # bit-identical here: a trip-count-1 loop gets unrolled by
            # XLA, whose fusion then diverges between the scan-with-
            # telemetry and bare-loop shapes.  Monitor callbacks stay live
            # for this one generation (no telemetry to flush).
            return self.workflow.step(state)
        if self.fused:
            # One lax.scan per segment: history capture, per-generation
            # best fitness and (optionally) the unhealthy-state early stop
            # ride inside the compiled program; returns (state, telemetry).
            return self.workflow._segment_program(state, n, self._fused_cfg())
        return jax.lax.fori_loop(
            0, n, lambda _, s: self.workflow.step(s), state
        )

    # -- events ------------------------------------------------------------
    def _event(
        self,
        msg: str,
        *,
        warn: bool = False,
        category: str = "runner",
        **payload: Any,
    ) -> None:
        """One supervisor event: always onto the obs bus (typed, with
        severity), AND through the legacy string callback / warning.

        Historical bug (fixed here, regression-tested in
        ``tests/test_obs.py``): with ``on_event`` set, warn-severity
        events used to reach only the callback as a bare string — the
        severity was silently dropped.  The bus now carries every event
        with its severity regardless of the callback."""
        if self.obs is not None:
            self.obs.event(
                category,
                msg,
                severity="warning" if warn else "info",
                **payload,
            )
        if self.on_event is not None:
            self.on_event(msg)
        elif warn:
            warnings.warn(msg)

    def _span(self, name: str, **args: Any):
        """A tracer span when the obs plane is live, else a no-op context
        — the one guard every instrumented wait/flush site shares."""
        if self.obs is not None:
            return self.obs.span(name, **args)
        return contextlib.nullcontext()

    # -- metrics -----------------------------------------------------------
    def _sync_counter(self, name: str, value: float, help: str = "") -> None:
        """Publish a run-scoped monotone stat as a process-level counter
        (delta against the per-run cursor; stats reset every ``run()``,
        counters never do)."""
        self.obs.registry.counter_sync(self._metric_cursor, name, value, help)

    def _publish_metrics(self, state: State | None = None) -> None:
        """Feed the registry from ``RunStats`` (and, when a state is at
        hand, the monitor's in-state counters) — called at segment
        boundaries and on every run exit, strictly host-side."""
        if self.obs is None:
            return
        s = self.stats
        self._sync_counter(
            "evox_runner_generations_total",
            s.completed_generations,
            "Generations completed by ResilientRunner.",
        )
        self._sync_counter(
            "evox_runner_segments_total", s.segments_run,
            "Compiled segments executed.",
        )
        self._sync_counter(
            "evox_runner_retries_total", s.retries, "Segment retries."
        )
        self._sync_counter(
            "evox_runner_watchdog_timeouts_total", s.watchdog_timeouts,
            "Segments abandoned past the watchdog deadline.",
        )
        self._sync_counter(
            "evox_runner_cpu_fallbacks_total", s.cpu_fallbacks,
            "Runs that fell back to the CPU backend.",
        )
        self._sync_counter(
            "evox_runner_restarts_total", len(s.restarts),
            "Health-triggered restart-policy firings.",
        )
        self._sync_counter(
            "evox_runner_health_checks_total", s.health_checks,
            "Boundary health probes run.",
        )
        self._sync_counter(
            "evox_runner_unhealthy_probes_total", s.unhealthy_probes,
            "Boundary health probes with unhealthy verdicts.",
        )
        self._sync_counter(
            "evox_runner_early_stops_total", s.early_stops,
            "Fused segments frozen early by the in-scan detector.",
        )
        self._sync_counter(
            "evox_runner_checkpoints_written_total", s.checkpoints_written,
            "Checkpoints durably published.",
        )
        self._sync_counter(
            "evox_runner_checkpoint_write_failures_total",
            s.checkpoint_write_failures,
            "Checkpoint writes that failed (run continued).",
        )
        self._sync_counter(
            "evox_runner_checkpoint_skips_total", len(s.checkpoint_skips),
            "Resume candidates rejected by the scan.",
        )
        self._sync_counter(
            "evox_runner_checkpoint_quarantines_total",
            sum(1 for k in s.checkpoint_skips if k.quarantined),
            "Byte-damaged checkpoints renamed *.corrupt.",
        )
        self._sync_counter(
            "evox_runner_preemptions_total", 1.0 if s.preempted else 0.0,
            "Graceful preemption stops (emergency checkpoint published).",
        )
        self._sync_counter(
            "evox_runner_checkpoint_block_seconds_total",
            s.checkpoint_block_seconds,
            "Wall seconds the generation loop spent blocked on "
            "checkpointing.",
        )
        if state is not None and "monitor" in state:
            mon = state["monitor"]
            # run_id label: gauges are last-write-wins, so two concurrent
            # runners sharing the process registry must not clobber each
            # other's boundary snapshots (counters aggregate fine
            # unlabeled; gauges do not).
            labels = (
                {"run_id": self.obs.run_id}
                if self.obs.run_id is not None
                else {}
            )
            for key in (
                "num_nonfinite",
                "num_shard_quarantines",
                "num_restarts",
                "num_preemptions",
            ):
                if key in mon:
                    self.obs.gauge(
                        f"evox_monitor_{key}",
                        "EvalMonitor in-state counter (boundary snapshot).",
                        **labels,
                    ).set(float(jax.device_get(mon[key])))

    def _publish_introspection(
        self, which: str, chunk: int | None, stepped: int
    ) -> None:
        """Segment-boundary device/program introspection (strictly
        host-side): live ``device.memory_stats()`` as ``evox_device_*``
        gauges (graceful no-op on stat-less CPU backends) plus Chrome-
        trace counter tracks (``ph:"C"`` — Perfetto draws live memory and
        generations/sec under the span timeline), and — when the AOT
        compile captured an XLA cost model for this program shape — the
        achieved-vs-peak roofline gauges, in-process (the live
        counterpart of ``tools/roofline.py``)."""
        if self.obs is None:
            return
        from ..obs import xla as obs_xla

        # Explicit device: the boundary runs right after a segment, so a
        # backend is guaranteed live — no need for obs.xla's no-init
        # probe of jax internals (which a jax upgrade could silence).
        stats = obs_xla.publish_device_memory_gauges(
            self.obs.registry, jax.local_devices()[0]
        )
        if stats:
            self.obs.record_counter(
                "device-memory",
                bytes_in_use=stats.get("bytes_in_use"),
                peak_bytes_in_use=stats.get("peak_bytes_in_use"),
            )
        seconds = self._last_exec_seconds
        gps = stepped / seconds if seconds > 0 and stepped else 0.0
        if gps:
            self.obs.record_counter("throughput", gens_per_sec=gps)
            labels = (
                {"run_id": self.obs.run_id}
                if self.obs.run_id is not None
                else {}
            )
            self.obs.gauge(
                "evox_runner_gens_per_sec",
                "Blocked-execution generations/sec of the latest segment.",
                **labels,
            ).set(gps)
        analysis = self._program_analysis.get((which, chunk))
        if analysis and gps and stepped:
            # Whole-program cost over the generations the scan covers —
            # per-generation normalization mirrors roofline_from_cost's
            # n_steps handling for fused whole-run profiles.
            per_gen = max(int(chunk) if chunk else 1, 1)
            result = obs_xla.roofline(
                flops_per_gen=analysis.get("flops", 0.0) / per_gen,
                bytes_per_gen=analysis.get("bytes_accessed", 0.0) / per_gen,
                gen_per_sec=gps,
            )
            label = which if chunk is None else f"{which}[{chunk}]"
            obs_xla.publish_roofline_gauges(
                self.obs.registry, label, result
            )

    # -- checkpointing -----------------------------------------------------
    def _ckpt_path(self, generation: int) -> Path:
        return self.checkpoint_dir / f"ckpt_{generation:08d}.npz"

    def _manifest_extras(self, probed: bool, state: State | None = None) -> dict:
        """Topology + health/restart context riding in the checkpoint
        manifest so a resumed run replays decisions exactly:

        * ``topology`` — the mesh-aware world this run executes under
          (overrides ``save_state``'s environment-level record), so resume
          can detect a topology change and re-mesh (``remesh=True``) or
          refuse loudly before touching the state;
        * ``restarts`` — the :class:`RestartEvent` lineage so far;
        * ``health_window`` — the probe's stagnation window *as of this
          write* (pre-probe for ordinary boundary checkpoints);
        * ``health_probed`` — whether this boundary was already probed
          before the write (post-restart checkpoints), i.e. whether a
          resume must re-probe it.
        """
        extras: dict = {
            "topology": workflow_topology(self.workflow).to_manifest()
        }
        # Numerics identity: the precision-policy tag and key impl the
        # workflow runs under ride in every manifest, so resume (and the
        # service's readmission scan) can refuse a cross-policy or
        # cross-impl load BEFORE restoring a single leaf — the remesh
        # discipline, applied to dtypes and PRNG streams.
        from ..precision import precision_tag

        extras["precision"] = precision_tag(
            getattr(self.workflow, "precision", None)
        )
        # The impl the state ACTUALLY carries (a knob-less workflow runs
        # pass-through on whatever impl the caller's key was; recording
        # the resolved default there would make the resume guard fire
        # falsely on those archives).  The knob-resolved fallback covers
        # key-leaf-less states — and still records an env-selected
        # generator (EVOX_TPU_KEY_IMPL) rather than leaving the guard
        # vacuous exactly when the knob was set fleet-wide.
        extras["key_impl"] = self._observed_key_impl(state)
        if self.health is not None:
            extras.update(
                restarts=[e.to_manifest() for e in self.stats.restarts],
                health_window=list(self.health.window),
                health_probed=bool(probed),
            )
        return extras

    def _observed_key_impl(self, state: State | None) -> str:
        """The PRNG impl name this run's numerics identity records: the
        impl of ``state``'s typed key leaves when it has any, else the
        workflow knob resolved through the env contract.  ONE definition
        for the manifest write side and the resume guard's expectation,
        so they can never disagree about a pass-through-keyed run."""
        from ..precision import resolve_key_impl, state_key_impl

        observed = None if state is None else state_key_impl(state)
        return observed or resolve_key_impl(
            getattr(self.workflow, "key_impl", None)
        )

    def _note_write_failure(self, path, exc: BaseException) -> None:
        """A checkpoint write failed (disk full, injected chaos, ...): the
        run goes on — the previous checkpoint remains the resume point, and
        because GC only fires after a successful durable publish, that
        previous checkpoint provably still exists."""
        name = Path(path).name
        self.stats.checkpoint_write_failures += 1
        self.stats.failures.append(
            f"checkpoint {name}: {type(exc).__name__}: {exc}"
        )
        self._event(
            f"checkpoint write of {name} failed ({type(exc).__name__}: "
            f"{exc}); continuing — the previous checkpoint remains the "
            f"resume point",
            warn=True,
            category="checkpoint",
            path=name,
            error=f"{type(exc).__name__}: {exc}",
        )

    def _gc_stale_checkpoints(self) -> None:
        """Delete all but the newest ``keep_checkpoints`` files.  Called
        only *after* a successful durable publish (inline on the sync
        path, from the writer's post-publish hook on the async path), so
        the last valid checkpoint can never be deleted ahead of its
        successor existing on disk.  Single-writer discipline: only the
        fleet's primary process ever GCs."""
        if not self.keep_checkpoints or not self.primary:
            return
        numbered = _numbered_checkpoints(self.checkpoint_dir)
        for _, stale in numbered[: -self.keep_checkpoints]:
            try:
                self.store.unlink(stale)
            except OSError:  # pragma: no cover - racing cleaners
                pass

    def _barrier_writer(self) -> None:
        """Wait out any in-flight async checkpoint write (no-op without a
        writer / pending work)."""
        if self._writer is not None:
            with self._span("checkpoint-barrier"):
                self._writer.barrier()

    def _fleet_sync(self) -> None:
        """Cross-host barrier at points where the single writer's disk
        state is about to be read fleet-wide (restart policies scanning
        the checkpoint directory).  No-op for single-process runs.  Every
        process reaches these call sites under identical control flow —
        boundary verdicts are pure functions of the replicated state — so
        the collective always matches up."""
        if jax.process_count() <= 1:
            return
        from ..parallel.multihost import fleet_barrier

        with self._span("fleet-barrier"):
            fleet_barrier("evox_tpu_runner_boundary")

    def _gather_state(self, state: State) -> State:
        """Make every state leaf process-addressable at a segment boundary.

        A multi-process program can hand back leaves sharded across hosts;
        boundary work (checkpoint serialization, restart policies, the
        final return) needs the full value on every host.  This is a
        collective (one all-gather per sharded leaf), executed by ALL
        processes at the same boundary — and it is also what keeps fleet
        runs bit-identical to their resumed reruns: every segment starts
        from a host-replicated state, exactly the placement a
        checkpoint-restored state has.  Single-process runs (and fleets
        whose state stayed replicated) pass through untouched."""
        if jax.process_count() <= 1:
            return state
        from ..parallel.multihost import gather_replicated

        return gather_replicated(state)

    def _beat(self, generation: int) -> None:
        """Publish a heartbeat progress beat for this boundary (no-op
        without a heartbeat)."""
        if self.heartbeat is not None:
            self.heartbeat.beat(
                generation=int(generation),
                segment_seconds=self._last_exec_seconds,
            )

    def _write_checkpoint(
        self,
        state: State,
        generation: int,
        *,
        probed: bool = False,
        emergency: bool = False,
        extra_metadata: dict | None = None,
    ) -> bool:
        """Publish ``state`` as ``ckpt_<generation>.npz``.

        Async by default: the call submits to the background writer (waiting
        only for a *previous* in-flight write) and returns; publication,
        the success event, and GC happen on the writer thread.  Emergency
        writes (preemption) are synchronous — the process is about to exit,
        so "submitted" is not good enough.  Returns whether a synchronous
        write succeeded (always True for async submissions).

        Single-writer discipline: a non-primary fleet process returns
        ``True`` without touching the directory — the primary's write of
        the identical (replicated) state IS this boundary's checkpoint."""
        if not self.primary:
            return True
        self.checkpoint_dir.mkdir(parents=True, exist_ok=True)
        path = self._ckpt_path(generation)
        metadata = self._manifest_extras(probed, state)
        if extra_metadata:
            metadata.update(extra_metadata)
        t0 = time.perf_counter()
        try:
            if self._writer is not None and not emergency:

                def _published(gen: int = generation) -> None:
                    self.stats.checkpoints_written += 1
                    self._event(
                        f"checkpoint written at generation {gen}",
                        category="checkpoint",
                        generation=gen,
                    )
                    self._gc_stale_checkpoints()

                self._writer.submit(
                    path,
                    state,
                    generation=generation,
                    metadata=metadata,
                    on_published=_published,
                )
                return True
            try:
                save_state(
                    path,
                    state,
                    generation=generation,
                    metadata=metadata,
                    store=self.store,
                    durable=True,
                )
            except (OSError, RuntimeError, ValueError) as e:
                self._note_write_failure(path, e)
                return False
            self.stats.checkpoints_written += 1
            self._event(
                f"checkpoint written at generation {generation}"
                + (" (emergency)" if emergency else ""),
                category="checkpoint",
                generation=generation,
                emergency=emergency,
            )
            self._gc_stale_checkpoints()
            return True
        finally:
            t1 = time.perf_counter()
            self.stats.checkpoint_block_seconds += t1 - t0
            if self.obs is not None:
                self.obs.record_span(
                    "checkpoint-submit",
                    t0,
                    t1,
                    generation=generation,
                    emergency=emergency,
                )

    def _pop_size_hint(self) -> int | None:
        """Population size for re-mesh divisibility checks, when the
        algorithm declares one (the standard single-objective/MO algorithm
        constructors all do).  ``None`` when the workflow evaluates through
        a padding ``ShardedProblem`` — padding makes any mesh size valid,
        so the divisibility gate must not fire."""
        from ..parallel import find_sharded

        sharded = find_sharded(getattr(self.workflow, "problem", None))
        if sharded is not None and sharded.pad:
            return None
        algo = self._base_algorithm or getattr(self.workflow, "algorithm", None)
        size = getattr(algo, "pop_size", None)
        return int(size) if isinstance(size, (int,)) else None

    def _skip_candidate(
        self, path: Path, reason: str, *, quarantined: bool = False
    ) -> None:
        """Record one rejected resume candidate: a structured
        :class:`CheckpointSkip` in ``stats.checkpoint_skips`` plus the
        human-readable event line."""
        self.stats.checkpoint_skips.append(
            CheckpointSkip(
                path=str(path), reason=reason, quarantined=quarantined
            )
        )
        if quarantined:
            self._event(
                f"quarantined unusable checkpoint {path.name} -> "
                f"{path.name}.corrupt: {reason}",
                warn=True,
            )
        else:
            self._event(
                f"skipping unusable checkpoint {path.name}: {reason}",
                warn=True,
            )

    def resume(self, template: State) -> tuple[State, int] | None:
        """Load the newest checkpoint that validates against ``template``.

        Returns ``(state, completed_generations)`` or ``None`` when no
        usable checkpoint exists.  The scan
        (:func:`scan_checkpoints(verify=True) <scan_checkpoints>`) first
        digest-verifies every candidate: byte-damaged files (torn writes,
        bit flips — what an unverified loader restores silently) are
        **quarantined** as ``*.corrupt``; candidates that are intact but
        fail template validation are skipped in place.  Every rejection is
        recorded as a structured :class:`CheckpointSkip` in
        ``stats.checkpoint_skips`` — newest-first fallback means one bad
        file (or several) cannot lose the run.

        Checkpoints written after a restart carry the restart lineage and
        the health probe's stagnation window in their manifest; resume
        replays the lineage (rebuilding the validation template when a
        restart changed state shapes — population regrows) and restores the
        window, so the continued run reaches bit-identical decisions.

        **Elastic resume.**  Manifests also record the mesh topology the
        checkpoint was written under.  When it differs from the current
        workflow's mesh, ``remesh=True`` (the default) repartitions the
        restored state over the new mesh and continues bit-identically;
        ``remesh=False`` raises a structured
        :class:`~evox_tpu.utils.CheckpointError` — a topology change is an
        operator decision, never something to silently paper over by
        starting fresh.
        """
        if not self.checkpoint_dir.is_dir():
            return None
        self._barrier_writer()  # scan must see every submitted write
        self._resumed_probed = False
        current_topo = workflow_topology(self.workflow)
        meshed = workflow_mesh(self.workflow)
        candidates, rejected = scan_checkpoints(
            self.checkpoint_dir,
            verify=self.verify_resume,
            # Quarantine renames are directory mutations: primary-only
            # (a read-only store would refuse them anyway — the flag keeps
            # the scan from even trying).
            quarantine=self.verify_resume and self.primary,
            store=self.store,
        )
        for path, reason, quarantined in rejected:
            self._skip_candidate(path, reason, quarantined=quarantined)
        for gen, path in reversed(candidates):
            try:
                manifest = read_manifest(path)
                if manifest.get("generation") not in (None, gen):
                    raise CheckpointError(
                        f"manifest generation {manifest['generation']} does "
                        f"not match filename generation {gen}"
                    )
            except FileNotFoundError:
                # Concurrent-cleaner race (fleet primary GC vs read-only
                # scanner): the candidate vanished — fall back, don't die.
                self._skip_candidate(
                    path, "vanished during resume (concurrent cleaner)"
                )
                continue
            except (CheckpointError, ValueError) as e:
                self._skip_candidate(path, str(e))
                continue
            # Topology gate OUTSIDE the skip-this-candidate handler: a mesh
            # mismatch with remesh disabled is an operator error that must
            # fail the resume loudly — silently skipping the checkpoint
            # would restart the run from scratch, losing exactly the work
            # elastic checkpoints exist to preserve.
            recorded_topo = check_topology(
                (manifest or {}).get("topology"),
                current_topo,
                remesh=self.remesh,
                pop_size=self._pop_size_hint(),
                pop_axis=meshed[1] if meshed is not None else None,
                context=f"checkpoint {path.name}",
            )
            topology_changed = topology_differs(recorded_topo, current_topo)
            try:
                try:
                    lineage = [
                        RestartEvent.from_manifest(d)
                        for d in (manifest or {}).get("restarts", [])
                    ]
                    # Each candidate is validated under ITS lineage: start
                    # from the base workflow configuration, then replay the
                    # recorded restarts on top (a previous candidate may
                    # have left the workflow mutated).
                    self._reset_base_algorithm()
                    candidate_template = template
                    if lineage and self.restart is not None:
                        candidate_template = self.restart.rebuild_template(
                            self.workflow, template, lineage, runner=self
                        )
                except (CheckpointError, ValueError):
                    raise
                except Exception as e:
                    # A malformed lineage entry (KeyError) or a failing
                    # user-supplied rebuild must skip THIS candidate, not
                    # abort the whole resume ("one bad file cannot lose
                    # the run").
                    raise CheckpointError(
                        f"restart lineage in manifest is unusable: {e!r}"
                    ) from e
                # allow_missing: state schemas gain leaves between versions
                # (PR 1 added num_nonfinite, this layer adds num_restarts /
                # corruption); a pre-upgrade checkpoint keeps the template's
                # value for new leaves (with a warning) instead of losing
                # the whole run to a schema bump.
                # Manifest-only scans defer the O(bytes) leaf-digest pass
                # to exactly the one candidate being restored.
                state = load_state(
                    path,
                    candidate_template,
                    allow_missing=True,
                    verify=self.verify_resume == "manifest",
                    precision=getattr(self.workflow, "precision", None),
                    key_impl=self._observed_key_impl(candidate_template),
                )
            except FileNotFoundError:
                self._skip_candidate(
                    path, "vanished during resume (concurrent cleaner)"
                )
                continue
            except CheckpointCorruptError as e:
                # Byte damage surfacing only at restore time (verify off, or
                # damage the digest pass cannot see): same quarantine as the
                # scan would have applied.
                quarantined = True
                try:
                    self.store.rename(path, _quarantine_target(path))
                except OSError:  # pragma: no cover - racing cleaners
                    quarantined = False
                self._skip_candidate(path, str(e), quarantined=quarantined)
                continue
            except (CheckpointError, ValueError) as e:
                self._skip_candidate(path, str(e))
                continue
            if topology_changed and meshed is not None:
                # Elastic re-mesh: the restored arrays are global, so all
                # that changes is their partitioning — shard the population
                # leaves over the new mesh, replicate the rest, and the
                # trajectory continues bit-identically (global-slot PRNG
                # folding makes evaluation topology-invariant).
                mesh, axis = meshed
                state = remesh_state(state, mesh, axis)
                self._event(
                    f"re-meshed {path.name}: written on a "
                    f"{recorded_topo.describe()}, resuming on a "
                    f"{current_topo.describe()}"
                )
            if lineage:
                self.stats.restarts = lineage
                self._event(
                    f"restored restart lineage of {len(lineage)} event(s) "
                    f"from {path.name}"
                )
            if self.health is not None and manifest:
                self.health.restore(manifest.get("health_window", []))
                self._resumed_probed = bool(
                    manifest.get("health_probed", False)
                )
            if manifest.get("preempted"):
                self.stats.resumed_after_preemption = True
                self._event(
                    f"{path.name} is an emergency checkpoint "
                    f"({manifest.get('preemption_reason', 'preempted')}); "
                    f"continuing the interrupted run"
                )
            self._event(f"resumed from {path.name} (generation {gen})")
            return state, gen
        # No candidate was usable: undo any workflow mutation a failed
        # candidate's lineage replay left behind, so the fresh start that
        # follows runs the base configuration.
        self._reset_base_algorithm()
        return None

    def _reset_base_algorithm(self) -> None:
        """Undo any restart-policy mutation of ``workflow.algorithm`` so a
        new run (or a resume candidate without lineage) starts from the
        configuration the runner was constructed with."""
        if (
            self._base_algorithm is not None
            and getattr(self.workflow, "algorithm", None)
            is not self._base_algorithm
        ):
            self.workflow.algorithm = self._base_algorithm
            self._rebind_workflow()

    # -- guarded execution -------------------------------------------------
    def _cpu_device(self):
        return jax.local_devices(backend="cpu")[0]

    @staticmethod
    def _with_deadline(fn: Callable[[], State], timeout: float, what: str) -> State:
        """Run ``fn()`` in a worker thread and abandon it past ``timeout``.

        A hung dispatch/compile (a ``block_until_ready`` stuck in backend
        init — the 25-minute BASELINE.md signature) cannot be interrupted,
        only outwaited: the worker is left to die with its backend and the
        supervisor proceeds to retry/fallback.
        """
        # A daemon thread, NOT a ThreadPoolExecutor: executor threads are
        # non-daemon and concurrent.futures joins them at interpreter exit,
        # so an abandoned worker wedged in a 25-minute backend hang would
        # block process shutdown for the rest of the outage.
        result: dict = {}

        def target() -> None:
            try:
                result["value"] = fn()
            except BaseException as e:  # noqa: BLE001 - re-raised below
                result["error"] = e

        worker = threading.Thread(
            target=target, name="evox-tpu-guard", daemon=True
        )
        worker.start()
        worker.join(timeout)
        if worker.is_alive():
            # The worker cannot be interrupted, only abandoned; being a
            # daemon it dies with the process instead of wedging exit.
            raise WatchdogTimeout(
                f"{what} exceeded the {timeout:.1f}s watchdog deadline "
                f"(hung dispatch — the backend-init hang signature); "
                f"abandoning the attempt"
            )
        if "error" in result:
            raise result["error"]
        return result["value"]

    def _abstract_sig(self, state: State):
        leaves, treedef = jax.tree_util.tree_flatten(state)
        return (
            treedef,
            tuple(
                (getattr(l, "shape", None), str(getattr(l, "dtype", type(l))))
                for l in leaves
            ),
        )

    def _get_executable(
        self, which: str, state: State, chunk: int | None
    ) -> Callable[[State], State]:
        """AOT-compile (once, cached) the program for this segment shape.

        Compiling outside the watchdog keeps cold-compile latency from
        eating the execution deadline; ``compile_timeout`` (when set) guards
        the compile itself against a hung backend.
        """
        sig = (which, chunk, self._forced_cpu, self._abstract_sig(state))
        fn = self._exec_cache.get(sig)
        if fn is not None:
            return fn
        if which == "init":
            traced = self._jit_init_step
            lower = lambda: self._jit_init_step.lower(state)  # noqa: E731
        else:
            traced = lambda s: self._jit_segment(s, chunk)  # noqa: E731
            lower = lambda: self._jit_segment.lower(state, chunk)  # noqa: E731
        compile_now = lambda: lower().compile()  # noqa: E731
        # The compile seconds used to be measured (excluded from the
        # wall-interval EMA) and thrown away; keep them — they feed
        # ``stats.segment_timings``, the compile histogram, and the
        # ``aot-compile`` trace span.
        pkey = None
        if self.exec_cache is not None:
            from ..utils.exec_cache import abstract_signature, compile_uncached

            label = which if chunk is None else f"{which}[{chunk}]"
            if self._forced_cpu:
                label += "[cpu]"
            # The abstract state signature covers shapes/dtypes but NOT
            # the program itself: two workflows with identically-shaped
            # states (same algorithm/pop/dim, different problem) would
            # collide in a shared cache and the second would silently
            # optimize the first's objective.  Salt the label with the
            # workflow's static-configuration digest (recomputed after
            # _rebind_workflow: restart policies swap the algorithm).
            if self._exec_cache_identity is None:
                from ..service.tenant import static_signature

                self._exec_cache_identity = static_signature(
                    self.workflow
                )[:16]
            label += f"[{self._exec_cache_identity}]"
            pkey = (label, abstract_signature(state))
            # A cache-destined compile must bypass jax's persistent
            # compilation cache (a cache-served executable serializes to
            # an undeserializable payload — see utils.exec_cache).
            base_compile = compile_now
            compile_now = lambda: compile_uncached(base_compile)  # noqa: E731
        t0 = time.perf_counter()
        exe = None
        if pkey is not None:
            # The load deserializes onto the device — the same class of
            # backend call the compile deadline exists to guard; a wedged
            # backend must surface as a WatchdogTimeout, not a silent
            # forever-hang that bypasses the watchdog contract.
            load = lambda: self.exec_cache.load(*pkey)  # noqa: E731
            if self.compile_timeout is not None:
                exe = self._with_deadline(
                    load, self.compile_timeout, "exec-cache load"
                )
            else:
                exe = load()
        loaded_from_cache = exe is not None
        if exe is None:
            if self.compile_timeout is not None:
                exe = self._with_deadline(
                    compile_now, self.compile_timeout, f"compile of {which}"
                )
            else:
                exe = compile_now()
            if pkey is not None:
                self.exec_cache.save(*pkey, exe)
        t1 = time.perf_counter()
        self._last_compile_seconds += t1 - t0
        if self.obs is not None:
            # Program introspection at the only moment it is free: the
            # compiled executable is in hand exactly once per program
            # shape.  cost_analysis()/memory_analysis() degrade to an
            # empty analysis on backends without a cost model — gauges
            # are skipped, the roofline below never fires, nothing
            # raises.
            from ..obs import xla as obs_xla

            analysis = obs_xla.program_analysis(exe)
            label = which if chunk is None else f"{which}[{chunk}]"
            self._program_analysis[(which, chunk)] = analysis
            obs_xla.publish_program_gauges(
                self.obs.registry, label, analysis
            )
            self.obs.record_span(
                "aot-compile", t0, t1, which=which, chunk=chunk,
                cached=loaded_from_cache, **analysis
            )
            if loaded_from_cache:
                self.obs.counter(
                    "evox_runner_exec_cache_loads_total",
                    "Segment programs loaded from the persistent "
                    "executable cache instead of compiling.",
                ).inc()
            else:
                self.obs.counter(
                    "evox_runner_compiles_total",
                    "Cold AOT compiles paid by the runner.",
                ).inc()
                self.obs.histogram(
                    "evox_runner_segment_compile_seconds",
                    "AOT compile seconds per compiled segment program.",
                ).observe(t1 - t0)

        def call(s: State, _exe=exe, _traced=traced, _sig=sig) -> State:
            try:
                return _exe(s)
            except (ValueError, TypeError) as e:
                # AOT executables are strict about input placement/layout
                # (e.g. mesh-sharded states); fall back to traced dispatch
                # for this signature, which re-places inputs as needed.
                if "sharding" in str(e).lower() or "layout" in str(e).lower():
                    self._exec_cache[_sig] = _traced
                    return _traced(s)
                raise

        self._exec_cache[sig] = call
        return call

    def _execute_once(
        self, which: str, state: State, chunk: int | None
    ) -> State:
        """One attempt: (cached) AOT compile, then watchdog-guarded
        execution to completion (``block_until_ready``)."""
        self._last_compile_seconds = 0.0
        if self._forced_cpu:
            state = jax.device_put(state, self._cpu_device())
            ctx = jax.default_device(self._cpu_device())
        else:
            ctx = contextlib.nullcontext()
        with ctx:
            exe = self._get_executable(which, state, chunk)
            run = lambda: jax.block_until_ready(exe(state))  # noqa: E731
            # Execution-only timing for the wall-interval chunk adapter:
            # _get_executable above may have paid a cold AOT compile, and
            # folding compile seconds into the per-generation EMA would
            # make the adapter shrink the chunk, compile the NEW length,
            # measure that compile too, and spiral every segment into a
            # fresh compile.
            t0 = time.perf_counter()
            try:
                if self.watchdog_timeout is None:
                    return run()
                return self._with_deadline(
                    run, self.watchdog_timeout, "segment execution"
                )
            finally:
                t1 = time.perf_counter()
                self._last_exec_seconds = t1 - t0
                if self.obs is not None:
                    self.obs.record_span(
                        "execute", t0, t1, which=which, chunk=chunk
                    )
                    self.obs.histogram(
                        "evox_runner_segment_execute_seconds",
                        "Blocked execution seconds per segment attempt.",
                    ).observe(t1 - t0)

    def _reload_for_retry(self, state: State, generation: int) -> State:
        """Best source of truth for a retry: the on-disk checkpoint of the
        segment's input generation (device buffers of ``state`` may belong
        to a dead backend); falls back to the in-memory state."""
        self._barrier_writer()  # the boundary write may still be in flight
        path = self._ckpt_path(generation)
        if path.exists():
            try:
                return load_state(
                    path,
                    state,
                    verify=self.verify_resume,
                    precision=getattr(self.workflow, "precision", None),
                    key_impl=self._observed_key_impl(state),
                )
            except (CheckpointError, ValueError) as e:  # pragma: no cover
                self._event(
                    f"retry reload of {path.name} failed ({e}); "
                    f"reusing in-memory state",
                    warn=True,
                )
        return state

    def _attempt(
        self,
        which: str,
        state: State,
        generation: int,
        desc: str,
        chunk: int | None = None,
    ) -> State:
        """Execute one segment with the full recovery ladder: retries with
        backoff, then (optionally) a CPU fallback with a fresh budget."""
        failures = 0
        while True:
            try:
                return self._execute_once(which, state, chunk)
            except Exception as e:  # noqa: BLE001 - predicate filters below
                if not self.retry.retryable(e):
                    raise
                failures += 1
                if isinstance(e, WatchdogTimeout):
                    self.stats.watchdog_timeouts += 1
                self.stats.failures.append(f"{desc}: {type(e).__name__}: {e}")
                if failures > self.retry.max_retries:
                    if self.cpu_fallback and not self._forced_cpu:
                        self._forced_cpu = True
                        self.stats.cpu_fallbacks += 1
                        failures = 0
                        self._event(
                            f"{desc}: retry budget exhausted; falling back "
                            f"to the CPU backend",
                            warn=True,
                        )
                        state = self._reload_for_retry(state, generation)
                        continue
                    raise ResilienceError(
                        f"{desc} failed after {self.retry.max_retries} "
                        f"retries"
                        + (" and a CPU fallback" if self._forced_cpu else "")
                    ) from e
                delay = self.retry.delay(failures)
                self.stats.retries += 1
                self._event(
                    f"{desc}: attempt {failures} failed "
                    f"({type(e).__name__}); retrying in {delay:.2f}s",
                    warn=True,
                )
                time.sleep(delay)
                state = self._reload_for_retry(state, generation)

    # -- run-health probing and restarts -----------------------------------
    def _controller_trend(self, done: int):
        """Consult the controller's trend plane with the flight window.
        Returns a fired :class:`~evox_tpu.control.Decision` or ``None``;
        never raises — a missing/detached flight recorder and any
        controller failure degrade to the threshold probes (the
        controller emits the structured warning + ``degrade`` decision,
        and this wrapper is the belt-and-braces outer guard)."""
        flight = self.obs.flight if self.obs is not None else None
        rows = None
        if flight is not None:
            try:
                rows = flight.rows()
            except Exception:  # noqa: BLE001 - detached/broken recorder
                rows = None
        try:
            return self.controller.trend_verdict(rows, generation=done)
        except Exception as e:  # noqa: BLE001 - advisory plane only
            self._event(
                f"controller trend consult failed ({type(e).__name__}: "
                f"{e}); continuing on threshold probes",
                warn=True,
                category="control",
            )
            return None

    def _health_boundary(
        self, state: State, done: int, n_steps: int
    ) -> tuple[State, int]:
        """Probe the state at a chunk boundary; apply the restart policy on
        an unhealthy verdict.

        Called exactly once per boundary (including right after a resume
        whose checkpoint was written pre-probe), so the probe's stagnation
        window — persisted in checkpoint manifests — advances identically
        in interrupted and uninterrupted runs.  Returns the (possibly
        restarted) state and generation count.
        """
        if self.health is None and self.controller is None:
            return state, done
        report: HealthReport | None = None
        if self.health is not None:
            with self._span("health-probe", generation=done):
                report = self.health.check(state, generation=done)
            self.stats.health_checks += 1
            self.stats.last_report = report
            if not report.healthy:
                self.stats.unhealthy_probes += 1
        # Controller trend overlay: a boundary the threshold probe calls
        # healthy may still be on a degenerate *trajectory* — the
        # controller reads the flight window and can fire the restart
        # machinery early.  An unhealthy probe verdict always wins (the
        # probe's detectors are the baseline the controller degrades to).
        trend_decision = None
        if (
            (report is None or report.healthy)
            and self.controller is not None
            and self.controller.trend_enabled
            and done < n_steps
        ):
            trend_decision = self._controller_trend(done)
            if trend_decision is not None:
                base = report if report is not None else HealthReport(
                    generation=done, healthy=True
                )
                report = base.with_trend(
                    [f"controller trend verdict: {trend_decision.action}"]
                )
                self.stats.last_report = report
        if report is None or report.healthy:
            return state, done
        reasons = "; ".join(report.reasons)
        if self.restart is None or done >= n_steps:
            self._event(
                f"unhealthy state at generation {done}: {reasons}",
                warn=True,
                category="health",
                generation=done,
                reasons=list(report.reasons),
            )
            return state, done
        if len(self.stats.restarts) >= self.max_restarts:
            self._event(
                f"unhealthy state at generation {done} ({reasons}) but the "
                f"restart budget of {self.max_restarts} is spent; continuing",
                warn=True,
                category="health",
                generation=done,
                reasons=list(report.reasons),
            )
            return state, done
        return self._fire_restart(state, done, n_steps, report, trend_decision)

    def _fire_restart(
        self,
        state: State,
        done: int,
        n_steps: int,
        report: HealthReport,
        trend_decision: Any = None,
    ) -> tuple[State, int]:
        """Apply the restart policy to an unhealthy boundary verdict:
        policy apply, lineage event, post-restart checkpoint + stale-future
        invalidation, fleet lockstep.  Extracted from
        :meth:`_health_boundary` so subclasses (the HPO runner's
        elastic-growth ladder) can fire the identical machinery with their
        own verdicts; callers guarantee a configured ``restart=`` policy
        and an unspent ``max_restarts`` budget."""
        reasons = "; ".join(report.reasons)
        # Restart policies read checkpoints from disk (rollback scans the
        # directory for candidates): flush the boundary's in-flight async
        # write first, so the policy sees the same directory a synchronous
        # writer would have produced — and its decisions stay replayable.
        # In a fleet, additionally barrier the other hosts on the primary's
        # flush: a non-primary policy must never scan a directory the
        # single writer is still publishing into.
        self._barrier_writer()
        self._fleet_sync()
        idx = len(self.stats.restarts)
        ctx = RestartContext(
            runner=self,
            workflow=self.workflow,
            state=state,
            generation=done,
            report=report,
            restart_index=idx,
            lineage=tuple(self.stats.restarts),
            decision=trend_decision,
        )
        new_state, new_done, needs_init, detail = self.restart.apply(ctx)
        if trend_decision is not None:
            # Record which plane fired in the lineage: the journaled
            # decision (seq) holds the full evidence.
            detail = {
                **detail,
                "trend": trend_decision.action,
                "decision_seq": trend_decision.seq,
            }
        event = RestartEvent(
            generation=done,
            policy=self.restart.name,
            restart_index=idx,
            reasons=list(report.reasons),
            detail=detail,
        )
        self.stats.restarts.append(event)
        self._event(
            f"restart #{idx + 1} ({self.restart.name}) at generation {done}: "
            f"{reasons}",
            warn=True,
            category="restart",
            policy=self.restart.name,
            generation=done,
            restart_index=idx,
            reasons=list(report.reasons),
        )
        # Give the restarted search a full window to prove itself: stale
        # pre-restart entries would otherwise re-trip the stagnation
        # detector at the very next boundary (the monitor's best-so-far is
        # monotone, so a restart can never improve it instantly) and
        # cascade restarts until the budget is gone.  The cleared window is
        # what later checkpoints persist, so replay stays deterministic.
        if self.health is not None:
            self.health.reset()
        # Count the restart into the monitor's in-state metrics so it is
        # visible from the checkpointed state itself (EvalMonitor surfaces
        # it as ``num_restarts``), not only from host-side stats.
        monitor = getattr(self.workflow, "monitor", None)
        if monitor is not None and "monitor" in new_state:
            new_state = new_state.replace(
                monitor=monitor.record_restart(new_state["monitor"])
            )
        if needs_init:
            # Fresh-setup policies hand back a pre-init state: drive it
            # through one init segment (with the full retry ladder) before
            # chunking resumes.  That evaluation costs one generation of
            # budget, like any other.
            new_state = self._attempt(
                "init",
                new_state,
                new_done,
                f"restart init (generation {new_done + 1})",
            )
            new_done += 1
            self.stats.segments_run += 1
        # Publish the post-restart state and invalidate the stale future:
        # checkpoints beyond it belong to the abandoned trajectory and must
        # not hijack a later resume.  Barrier so the publish (and its GC)
        # lands before we enumerate the directory for the invalidation.
        self._write_checkpoint(new_state, new_done, probed=not needs_init)
        self._barrier_writer()
        if self.primary:
            for gen, path in _numbered_checkpoints(self.checkpoint_dir):
                if gen > new_done:
                    try:
                        self.store.unlink(path)
                    except OSError:  # pragma: no cover - racing cleaners
                        pass
        # Fleet lockstep: non-primary hosts must not run on past a restart
        # while the single writer is still invalidating the stale future.
        self._fleet_sync()
        self.stats.completed_generations = new_done
        if needs_init:
            # The post-init state is a fresh boundary of its own: probe it
            # (the restart budget bounds recursion depth).
            return self._health_boundary(new_state, new_done, n_steps)
        return new_state, new_done

    # -- preemption --------------------------------------------------------
    def _handle_preemption(self, state: State, done: int, probed: bool):
        """The guard tripped: flush in-flight writes, publish an emergency
        checkpoint marked ``preempted`` (with the monitor's
        ``num_preemptions`` counter bumped *in the saved state*, so the
        count survives into the resumed run), and raise
        :class:`~evox_tpu.resilience.Preempted`.  The caller's ``finally``
        restores the signal handlers."""
        reason = self.preemption.reason or "preempted"
        # The boundary's regular checkpoint may still be in flight: barrier
        # so the emergency write below is strictly the newest publish.
        self._barrier_writer()
        monitor = getattr(self.workflow, "monitor", None)
        if monitor is not None and "monitor" in state:
            state = state.replace(
                monitor=monitor.record_preemption(state["monitor"])
            )
        ok = self._write_checkpoint(
            state,
            done,
            probed=probed,
            emergency=True,
            extra_metadata={"preempted": True, "preemption_reason": reason},
        )
        self.stats.preempted = True
        self.stats.preemption_reason = reason
        path = self._ckpt_path(done)
        outcome = (
            "published"
            if ok
            else "FAILED — prior boundary checkpoint remains the resume point"
        )
        self._event(
            f"preempted at generation {done} ({reason}); emergency "
            f"checkpoint {outcome}",
            warn=True,
            category="preemption",
            generation=done,
            reason=reason,
            checkpoint_published=ok,
        )
        self._publish_metrics(state)
        raise Preempted(
            f"run preempted at generation {done} ({reason}); rerun the same "
            f"supervisor to resume bit-identically from "
            f"{path.name if ok else 'the previous checkpoint'}",
            generation=done,
            reason=reason,
            checkpoint=path if ok else None,
        )

    # -- wall-clock checkpoint cadence ---------------------------------------
    def _next_chunk(self) -> int:
        if self.controller is not None and self.controller.cadence_enabled:
            chunk = None
            try:
                chunk = self.controller.next_chunk(
                    self.stats.segment_timings,
                    checkpoint_every=self.checkpoint_every,
                    generation=self.stats.completed_generations,
                    current=self._controller_chunk,
                )
            except Exception as e:  # noqa: BLE001 - advisory plane only
                # Belt and braces: the controller guards itself, but a
                # broken controller must never take the run with it.
                self._event(
                    f"controller cadence consult failed "
                    f"({type(e).__name__}: {e}); keeping the configured "
                    f"cadence",
                    warn=True,
                    category="control",
                )
            if chunk:
                self._controller_chunk = int(chunk)
                return self._controller_chunk
        if self.checkpoint_wall_interval is None:
            return self.checkpoint_every
        return self._adaptive_chunk

    def _adapt_chunk(self, chunk: int, seconds: float) -> None:
        """Steer the chunk length toward ``checkpoint_wall_interval``
        seconds per segment (EMA-smoothed per-generation wall time),
        quantized to powers of two so at most ``log2(checkpoint_every)``
        distinct segment programs ever compile.

        The quantizer picks the NEXT segment's scan length — a fused
        segment is one compiled ``lax.scan`` and cannot be shortened
        mid-flight, so the decision always lands at the boundary before
        the next scan is dispatched (``_next_chunk``), never by
        retroactively splitting the segment already running.  ``seconds``
        must be execution-only wall time (``_execute_once`` measures it
        past the AOT compile): with compile time folded in, every length
        change would measure its own cold compile as "slow generations",
        shrink the chunk again, compile the new length, and spiral every
        segment into a fresh compile — the lost-work-bound regression
        ``tests/test_fused_segment.py`` pins."""
        if self.checkpoint_wall_interval is None:
            return
        per_gen = max(seconds / max(chunk, 1), 1e-9)
        self._per_gen_ema = (
            per_gen
            if self._per_gen_ema is None
            else 0.5 * self._per_gen_ema + 0.5 * per_gen
        )
        target = self.checkpoint_wall_interval / self._per_gen_ema
        quantized = 1
        while quantized * 2 <= target and quantized * 2 <= self.checkpoint_every:
            quantized *= 2
        self._adaptive_chunk = quantized

    # -- the supervisor loop -----------------------------------------------
    def run(
        self,
        state: State,
        n_steps: int,
        *,
        fresh: bool = False,
    ) -> State:
        """Run ``n_steps`` total generations (``init_step`` + ``n_steps - 1``
        ``step``s, matching ``StdWorkflow.run``), surviving backend loss.

        :param state: the initial workflow state — also the *template* a
            checkpoint must validate against when resuming.
        :param n_steps: total generations for the whole run (not the
            remainder): a resumed run passes the same ``n_steps`` and the
            runner fast-forwards past the completed prefix.
        :param fresh: start from ``state`` instead of resuming; existing
            checkpoints in the directory are DELETED first (quarantined
            ``*.corrupt`` files included) so the new run's lineage cannot
            interleave with (or resume into) a stale one.
        :returns: the final state, identical to what an uninterrupted
            ``workflow.run(state, n_steps)`` would have produced.  Any
            async checkpoint write is barriered before control returns —
            on exit (normal or not), the newest submitted checkpoint is
            durably on disk.
        :raises Preempted: the :class:`PreemptionGuard` tripped; the
            emergency checkpoint is published and rerunning resumes it.
        """
        if n_steps < 1:
            raise ValueError(f"n_steps must be >= 1, got {n_steps}")
        self.stats = RunStats()
        # The metric cursor tracks stats: both reset together, so counter
        # deltas stay non-negative across runs of one runner.
        self._metric_cursor = {}
        # A previous run's CPU fallback must not pin THIS run to the CPU
        # backend: give the (possibly recovered) accelerator a fresh chance.
        self._forced_cpu = False
        # Likewise, a previous run's restarts must not leak search
        # configuration or probe history into this one; resume restores
        # both from the checkpoint manifest as needed.
        self._reset_base_algorithm()
        self._resumed_probed = False
        self._adaptive_chunk = 1
        self._per_gen_ema = None
        # Controller cadence resumes from the configured chunk each run;
        # the controller itself (decisions, degrade latches, quiet
        # windows) persists — its journal is cross-run by design.
        self._controller_chunk = self.checkpoint_every
        if self.health is not None:
            self.health.reset()
        installed_guard = False
        if self.preemption is not None:
            if self._owns_guard:
                # A fresh run through a runner-owned guard: the flag from a
                # previous run's preemption must not re-trip this one.
                self.preemption.reset()
            if not self.preemption.installed:
                self.preemption.install()
                installed_guard = True
        try:
            with self._span("run", n_steps=n_steps):
                return self._run_supervised(state, n_steps, fresh)
        finally:
            # The newest submitted checkpoint must be durably on disk by
            # the time control leaves the supervisor — whether the run
            # finished, failed, or was preempted.  This wait blocks the
            # caller like any other checkpoint stall, so it counts into
            # checkpoint_block_seconds (the bench's async number would
            # otherwise understate by up to one full write per run).
            t0 = time.perf_counter()
            self._barrier_writer()
            self.stats.checkpoint_block_seconds += time.perf_counter() - t0
            # Final registry sync: async-writer publishes that landed
            # during the barrier, the terminal block-seconds, failures.
            self._publish_metrics()
            if installed_guard:
                self.preemption.uninstall()

    def _run_supervised(self, state: State, n_steps: int, fresh: bool) -> State:
        done = 0
        probed = False
        if fresh and self.primary and self.checkpoint_dir.is_dir():
            # Clear the old lineage: stale higher-generation files would
            # otherwise survive pruning (which keeps the N highest numbers)
            # and hijack the next resume.  Quarantined files go too — they
            # are evidence of the OLD lineage's storage, not this run's.
            # Single-writer: only the primary clears (fresh runs never read
            # the directory, so peers have nothing to race).
            self._barrier_writer()
            for _, path in _numbered_checkpoints(self.checkpoint_dir):
                try:
                    self.store.unlink(path)
                except OSError:  # pragma: no cover - racing cleaners
                    pass
            for path in self.checkpoint_dir.glob("ckpt_*.npz.corrupt*"):
                try:
                    self.store.unlink(path)
                except OSError:  # pragma: no cover - racing cleaners
                    pass
        if not fresh:
            resumed = self.resume(state)
            if resumed is not None:
                state, done = resumed
                if done > n_steps:
                    raise ValueError(
                        f"checkpoint at generation {done} is beyond "
                        f"n_steps={n_steps}; pass fresh=True to restart or "
                        f"point at a different checkpoint_dir"
                    )
                self.stats.resumed_from_generation = done
                self.stats.completed_generations = done
                probed = self._resumed_probed
                # Publish a progress beat immediately: a fleet supervisor
                # watching a relaunched worker must see it land on its
                # resume point, not wait a whole first segment.
                self._beat(done)
        if done == 0:
            # The init segment is segment index 0 of a fresh run for the
            # opt-in profiler window (a resumed run has no init segment,
            # so its first loop segment takes index 0 instead — the index
            # counts segments executed by THIS run()).
            profile_ctx = (
                self.obs.maybe_profile(self.stats.segments_run)
                if self.obs is not None
                else contextlib.nullcontext()
            )
            with profile_ctx:
                state = self._attempt(
                    "init", state, 0, "init_step (generation 1)"
                )
            state = self._gather_state(state)
            done = 1
            self.stats.segments_run += 1
            self.stats.completed_generations = done
            blocked0 = self.stats.checkpoint_block_seconds
            self._write_checkpoint(state, done)
            self._record_segment_timing(done, blocked0)
            self._publish_metrics(state)
            self._publish_introspection("init", None, 1)
            self._beat(done)
            probed = False
        while True:
            # Preemption is checked at every boundary, BEFORE more work is
            # queued: the scheduler's grace window is spent publishing the
            # emergency checkpoint, not computing a segment that would be
            # killed midway.  A trip with no work left is ignored — a run
            # that already computed its final generation returns its state
            # like any completed run, instead of discarding it behind a
            # Preempted raise.
            if (
                done < n_steps
                and self.preemption is not None
                and self.preemption.triggered
            ):
                self._handle_preemption(state, done, probed)
            if not probed:
                # Every boundary is probed exactly once — ordinary
                # checkpoints are written pre-probe, so a resume re-probes
                # its landing boundary and reaches the same verdict an
                # uninterrupted run did.
                state, done = self._health_boundary(state, done, n_steps)
                probed = True
            if done >= n_steps:
                break
            chunk = min(self._next_chunk(), n_steps - done)
            # Opt-in device profiling of exactly the Nth segment executed
            # by this run() (fresh runs: init segment = 0): one
            # jax.profiler.trace window, no profiler cost anywhere else.
            profile_ctx = (
                self.obs.maybe_profile(self.stats.segments_run)
                if self.obs is not None
                else contextlib.nullcontext()
            )
            with profile_ctx:
                result = self._attempt(
                    "segment",
                    state,
                    done,
                    f"segment (generations {done + 1}..{done + chunk})",
                    chunk=chunk,
                )
            if self.fused and chunk > 1:
                state, stepped = self._consume_telemetry(result, done, chunk)
            else:
                # Debug path, or the shared single-step ragged tail (see
                # _segment): the result is the bare state.
                state, stepped = result, chunk
            # Boundary gather (multi-process fleets only): leaves the
            # program left sharded across hosts come back addressable, so
            # checkpointing, probes, and restart policies see full values —
            # and every segment starts from the same host-replicated
            # placement a resumed run starts from (bit-identity).
            state = self._gather_state(state)
            # Adapt on the EXECUTION seconds of this segment (compile time
            # excluded — see _execute_once), normalized by the generations
            # that actually ran.
            self._adapt_chunk(stepped, self._last_exec_seconds)
            done += stepped
            self.stats.segments_run += 1
            self.stats.chunk_sizes.append(stepped)
            self.stats.completed_generations = done
            blocked0 = self.stats.checkpoint_block_seconds
            self._write_checkpoint(state, done)
            self._record_segment_timing(done, blocked0)
            self._publish_metrics(state)
            self._publish_introspection("segment", chunk, stepped)
            self._beat(done)
            probed = False
        return state

    def _record_segment_timing(self, done: int, blocked_before: float) -> None:
        """Keep where this segment's wall clock went: the AOT compile the
        boundary paid (0 once cached), blocked execution, and the
        checkpoint submit+barrier block — the split ROADMAP item 1's
        dispatch-overhead hunt needs per segment, not just as run totals."""
        self.stats.segment_timings.append(
            SegmentTiming(
                generation=done,
                compile_seconds=self._last_compile_seconds,
                execute_seconds=self._last_exec_seconds,
                checkpoint_block_seconds=(
                    self.stats.checkpoint_block_seconds - blocked_before
                ),
            )
        )

    def _consume_telemetry(
        self, result, done: int, chunk: int
    ) -> tuple[State, int]:
        """Boundary-side handling of a fused segment's ``(state,
        telemetry)`` result: one ``device_get`` for the whole batch, the
        monitor-history flush (the batched stand-in for the per-generation
        callbacks — flushed only for *successful* segments, so retries
        never duplicate history entries), and the early-stop accounting.
        Returns ``(state, generations_actually_executed)``."""
        state, telemetry = result
        with self._span("telemetry-flush", generation=done):
            # Telemetry leaves can come back process-sharded like state
            # leaves (the gather no-ops single-process and on replicated
            # trees).
            host = jax.device_get(self._gather_state(telemetry))
            self.workflow.flush_telemetry(host)
        executed = int(host["executed"])
        if (
            self.obs is not None
            and self.obs.flight is not None
            and "flight" in host
        ):
            # Feed the black box BEFORE any boundary verdict fires: the
            # restart/early-stop/preemption events published below and by
            # _health_boundary trigger the recorder's bundle dump, which
            # must see this segment's rows.
            self.obs.flight.record_rows(
                host["flight"], executed, start_generation=done
            )
        if bool(host["stopped"]) and executed < chunk:
            self.stats.early_stops += 1
            self._event(
                f"fused segment stopped early at generation "
                f"{done + executed}: unhealthy state detected in-scan; the "
                f"remaining {chunk - executed} generation(s) of the "
                f"segment were frozen no-ops",
                warn=True,
                category="health",
                generation=done + executed,
                kind="early_stop",
                frozen_generations=chunk - executed,
            )
        return state, executed
