"""Resilient long-run execution.

Production-scale evolutionary computation assumes runs that survive
accelerator loss: this repo's own measurement log (BASELINE.md) records two
full benchmark rounds lost to TPU backend outages — 21 consecutive probes
hanging ~25 minutes in backend init and exiting ``UNAVAILABLE`` — while the
bare ``StdWorkflow.run`` fori-loop discards the whole run on any crash.

This subsystem adds the missing layer:

* :class:`ResilientRunner` — wraps any :class:`~evox_tpu.core.Workflow` and
  executes N generations as chunked jitted segments with periodic atomic
  checkpoints, auto-resume from the latest valid checkpoint, retry with
  exponential backoff on backend-loss signatures (``UNAVAILABLE`` /
  ``INTERNAL`` ``XlaRuntimeError``), a watchdog deadline that converts the
  silent-hang signature into a retryable timeout, and an optional last-ditch
  CPU fallback.
* :class:`HealthProbe` / :class:`HealthReport` — run-health diagnostics
  between chunks: non-finite leaves anywhere in the state pytree, population
  diversity collapse, ES step-size out-of-range, and best-fitness stagnation
  — degenerate-search failure modes that never raise but waste the whole
  remaining budget.
* Restart policies (:class:`RollbackToCheckpoint`,
  :class:`ReinitLargerPopulation`, :class:`PerturbAroundBest`) — applied by
  the runner on an unhealthy verdict: rollback with perturbed PRNG streams,
  IPOP-style population regrow with the elite preserved, or re-seeding
  around the incumbent best.  All deterministic and bit-reproducible under
  resume; fired restarts are recorded as :class:`RestartEvent` lineage in
  ``RunStats`` and in every checkpoint manifest.
* :class:`FaultyProblem` — a deterministic fault-injection wrapper (NaN/Inf
  rows, in-state corruption, stagnation plateaus, host-side exceptions,
  artificial delays, dead/straggler shard schedules, an eval deadline with
  penalty fallback — all by evaluation schedule) so every recovery path
  above is testable on CPU.
* Elastic topology (``elastic.py``) — checkpoint manifests record the mesh
  topology they were written under (:class:`MeshTopology`), and the runner's
  resume **re-meshes**: a run checkpointed on an N-device ``pop`` mesh
  continues bit-identically on M devices (:func:`check_topology` gates,
  :func:`remesh_state` repartitions), because checkpointed state is global
  and per-individual PRNG streams fold the global slot index
  (``parallel/sharded_problem.py``).

Non-finite fitness quarantine lives in the workflow layer itself
(``StdWorkflow(quarantine_nonfinite=True)``, the default) so NaN/±Inf never
silently propagate through ranking — see ``workflows/std_workflow.py``.
"""

from .elastic import (
    MeshTopology,
    check_topology,
    current_topology,
    remesh_state,
    topology_differs,
    workflow_mesh,
    workflow_topology,
)
from .faults import FaultyProblem, InjectedBackendError, InjectedFatalError
from .health import HealthProbe, HealthReport
from .restart import (
    PerturbAroundBest,
    ReinitLargerPopulation,
    RestartContext,
    RestartEvent,
    RestartPolicy,
    RollbackToCheckpoint,
    incumbent_best,
    perturb_prng_keys,
)
from .runner import (
    ResilienceError,
    ResilientRunner,
    RetryPolicy,
    RunStats,
    WatchdogTimeout,
    default_retryable,
    latest_checkpoint,
)

__all__ = [
    "MeshTopology",
    "check_topology",
    "current_topology",
    "remesh_state",
    "topology_differs",
    "workflow_mesh",
    "workflow_topology",
    "ResilientRunner",
    "RetryPolicy",
    "RunStats",
    "ResilienceError",
    "WatchdogTimeout",
    "default_retryable",
    "latest_checkpoint",
    "HealthProbe",
    "HealthReport",
    "RestartPolicy",
    "RestartEvent",
    "RestartContext",
    "RollbackToCheckpoint",
    "ReinitLargerPopulation",
    "PerturbAroundBest",
    "incumbent_best",
    "perturb_prng_keys",
    "FaultyProblem",
    "InjectedBackendError",
    "InjectedFatalError",
]
