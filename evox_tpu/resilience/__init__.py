"""Resilient long-run execution.

Production-scale evolutionary computation assumes runs that survive
accelerator loss: this repo's own measurement log (BASELINE.md) records two
full benchmark rounds lost to TPU backend outages — 21 consecutive probes
hanging ~25 minutes in backend init and exiting ``UNAVAILABLE`` — while the
bare ``StdWorkflow.run`` fori-loop discards the whole run on any crash.

This subsystem adds the missing layer:

* :class:`ResilientRunner` — wraps any :class:`~evox_tpu.core.Workflow` and
  executes N generations as chunked jitted segments with periodic atomic
  checkpoints, auto-resume from the latest valid checkpoint, retry with
  exponential backoff on backend-loss signatures (``UNAVAILABLE`` /
  ``INTERNAL`` ``XlaRuntimeError``), a watchdog deadline that converts the
  silent-hang signature into a retryable timeout, and an optional last-ditch
  CPU fallback.
* :class:`FaultyProblem` — a deterministic fault-injection wrapper (NaN
  rows, host-side exceptions, artificial delays, by generation schedule) so
  every recovery path above is testable on CPU.

Non-finite fitness quarantine lives in the workflow layer itself
(``StdWorkflow(quarantine_nonfinite=True)``, the default) so NaN/±Inf never
silently propagate through ranking — see ``workflows/std_workflow.py``.
"""

from .faults import FaultyProblem, InjectedBackendError, InjectedFatalError
from .runner import (
    ResilienceError,
    ResilientRunner,
    RetryPolicy,
    RunStats,
    WatchdogTimeout,
    default_retryable,
    latest_checkpoint,
)

__all__ = [
    "ResilientRunner",
    "RetryPolicy",
    "RunStats",
    "ResilienceError",
    "WatchdogTimeout",
    "default_retryable",
    "latest_checkpoint",
    "FaultyProblem",
    "InjectedBackendError",
    "InjectedFatalError",
]
