"""Resilient long-run execution.

Production-scale evolutionary computation assumes runs that survive
accelerator loss: this repo's own measurement log (BASELINE.md) records two
full benchmark rounds lost to TPU backend outages — 21 consecutive probes
hanging ~25 minutes in backend init and exiting ``UNAVAILABLE`` — while the
bare ``StdWorkflow.run`` fori-loop discards the whole run on any crash.

This subsystem adds the missing layer:

* :class:`ResilientRunner` — wraps any :class:`~evox_tpu.core.Workflow` and
  executes N generations as chunked jitted segments with periodic atomic
  checkpoints, auto-resume from the latest valid checkpoint, retry with
  exponential backoff on backend-loss signatures (``UNAVAILABLE`` /
  ``INTERNAL`` ``XlaRuntimeError``), a watchdog deadline that converts the
  silent-hang signature into a retryable timeout, and an optional last-ditch
  CPU fallback.
* :class:`HealthProbe` / :class:`HealthReport` — run-health diagnostics
  between chunks: non-finite leaves anywhere in the state pytree, population
  diversity collapse, ES step-size out-of-range, and best-fitness stagnation
  — degenerate-search failure modes that never raise but waste the whole
  remaining budget.
* Restart policies (:class:`RollbackToCheckpoint`,
  :class:`ReinitLargerPopulation`, :class:`PerturbAroundBest`) — applied by
  the runner on an unhealthy verdict: rollback with perturbed PRNG streams,
  IPOP-style population regrow with the elite preserved, or re-seeding
  around the incumbent best.  All deterministic and bit-reproducible under
  resume; fired restarts are recorded as :class:`RestartEvent` lineage in
  ``RunStats`` and in every checkpoint manifest.
* :class:`PreemptionGuard` / :class:`Preempted` (``preemption.py``) —
  signal-aware graceful shutdown: SIGTERM/SIGINT (how schedulers and TPU
  preemption actually kill jobs) and provider maintenance events become a
  flag the runner checks at segment boundaries; on trip it barriers any
  in-flight async write, publishes an emergency checkpoint marked
  ``preempted``, restores prior handlers, and raises :class:`Preempted` —
  the next invocation auto-resumes bit-identically.
* Self-verifying async checkpointing — checkpoints carry per-leaf SHA-256
  digests (``utils/checkpoint.py``), the runner's resume scan
  (:func:`scan_checkpoints`) quarantines byte-damaged files as
  ``*.corrupt`` and falls back to the newest intact one, and writes run on
  a background :class:`~evox_tpu.utils.AsyncCheckpointWriter` (at most one
  in flight, durable atomic publish, GC strictly after the successor
  publishes) so the device loop never blocks on disk.
* :class:`FaultyProblem` — a deterministic fault-injection wrapper (NaN/Inf
  rows, in-state corruption, stagnation plateaus, host-side exceptions,
  artificial delays, SIGTERM-to-self, dead/straggler shard schedules, an
  eval deadline with penalty fallback — all by evaluation schedule) so
  every recovery path above is testable on CPU.
* :class:`FaultyStore` — the storage-side chaos twin: torn publishes, bit
  flips, ``ENOSPC``/``EIO``, crash-between-temp-and-rename, and slow disks
  by save schedule, so the checkpoint pipeline itself (including mid-write
  preemption and GC ordering) is testable deterministically.
* :class:`FaultyTransport` (``transport.py``) — the wire-side chaos twin:
  dropped/duplicated/torn/delayed requests **and replies** by request
  schedule, wrapping the gateway client's transport seam, so the network
  front door's exactly-once admission contract is testable
  deterministically (the dropped-*reply* case is the post-journal-append
  crash window seen from the wire).
* Chaos conduction (``chaos.py`` / ``invariants.py`` / ``testing.py``) —
  a seeded, JSON-serializable :class:`ChaosPlan` composes every fault
  plane above (process SIGKILL to members/router, disk, wire, and lane
  faults, partition/straggle windows) into one deterministic timeline;
  :class:`ChaosConductor` drives a routed multi-member fleet through it,
  journaling every injected event (bit-for-bit reproducible from
  ``(seed, plan digest)``) while continuously auditing the global
  invariant registry (:data:`INVARIANTS` — exactly-once admission,
  reply-after-journal, single-writer-per-namespace,
  no-acked-record-lost, bounded disk, monotone counters, SLO
  accounting); each :class:`InvariantViolation` is dumped as a
  structured postmortem evidence bundle through the
  :class:`~evox_tpu.obs.FlightRecorder` path.  ``testing.py`` is the
  public kill-at-every-boundary scaffolding the acceptance suites (and
  downstream users) drive.
* Elastic topology (``elastic.py``) — checkpoint manifests record the mesh
  topology they were written under (:class:`MeshTopology`), and the runner's
  resume **re-meshes**: a run checkpointed on an N-device ``pop`` mesh
  continues bit-identically on M devices (:func:`check_topology` gates,
  :func:`remesh_state` repartitions), because checkpointed state is global
  and per-individual PRNG streams fold the global slot index
  (``parallel/sharded_problem.py``).

* Fleet supervision (``fleet.py``) — host-level resilience for
  ``jax.distributed`` multi-host runs: :class:`FleetSupervisor` launches N
  worker processes with the ``EVOX_TPU_FLEET_*`` bootstrap contract
  (``evox_tpu.parallel.bootstrap_fleet``), watches exit codes plus the
  heartbeat plane (``evox_tpu.parallel.FleetHealth``) for dead / wedged /
  straggling hosts, stops survivors gracefully (SIGTERM → emergency
  checkpoint at the boundary, SIGKILL after the grace window), and
  relaunches on the surviving process count — elastic resume makes the
  continued run bit-identical to an uninterrupted run at that world size.
  Checkpoint I/O runs a single-writer discipline: process 0 publishes,
  GCs, and quarantines; every other process holds a
  :class:`~evox_tpu.utils.ReadOnlyCheckpointStore`.  Fleet chaos (host
  SIGKILL, coordinator partition, per-host slowdown) lives in
  :class:`FaultyProblem`'s ``kill_process_at`` /
  ``partition_process_at`` / ``slow_process_at`` schedules.

Non-finite fitness quarantine lives in the workflow layer itself
(``StdWorkflow(quarantine_nonfinite=True)``, the default) so NaN/±Inf never
silently propagate through ranking — see ``workflows/std_workflow.py``.
"""

from .elastic import (
    MeshTopology,
    check_topology,
    current_topology,
    remesh_state,
    topology_differs,
    workflow_mesh,
    workflow_topology,
)
from .faults import (
    FaultyProblem,
    FaultyStore,
    InjectedBackendError,
    InjectedFatalError,
    InjectedStorageError,
)
from .invariants import (
    INVARIANTS,
    AuditContext,
    InvariantViolation,
    audit_invariants,
)
from .schedule import validate_schedule
from .fleet import (
    EX_PREEMPTED,
    FleetError,
    FleetStats,
    FleetSupervisor,
    WorkerSpec,
    free_coordinator_port,
)
from .health import HealthProbe, HealthReport
from .preemption import Preempted, PreemptionGuard
from .restart import (
    PerturbAroundBest,
    ReinitLargerPopulation,
    RestartContext,
    RestartEvent,
    RestartPolicy,
    RollbackToCheckpoint,
    incumbent_best,
    perturb_prng_keys,
)
from .transport import FaultyTransport, TransportError
from .runner import (
    CheckpointSkip,
    ResilienceError,
    ResilientRunner,
    RetryPolicy,
    RunStats,
    SegmentTiming,
    WatchdogTimeout,
    default_retryable,
    latest_checkpoint,
    scan_checkpoints,
)

__all__ = [
    "MeshTopology",
    "check_topology",
    "current_topology",
    "remesh_state",
    "topology_differs",
    "workflow_mesh",
    "workflow_topology",
    "ResilientRunner",
    "RetryPolicy",
    "RunStats",
    "SegmentTiming",
    "CheckpointSkip",
    "ResilienceError",
    "WatchdogTimeout",
    "default_retryable",
    "latest_checkpoint",
    "scan_checkpoints",
    "PreemptionGuard",
    "Preempted",
    "HealthProbe",
    "HealthReport",
    "RestartPolicy",
    "RestartEvent",
    "RestartContext",
    "RollbackToCheckpoint",
    "ReinitLargerPopulation",
    "PerturbAroundBest",
    "incumbent_best",
    "perturb_prng_keys",
    "FaultyProblem",
    "FaultyStore",
    "FaultyTransport",
    "TransportError",
    "InjectedBackendError",
    "InjectedFatalError",
    "InjectedStorageError",
    "FleetSupervisor",
    "FleetError",
    "FleetStats",
    "WorkerSpec",
    "EX_PREEMPTED",
    "free_coordinator_port",
    "validate_schedule",
    "AuditContext",
    "InvariantViolation",
    "INVARIANTS",
    "audit_invariants",
    "ChaosPlan",
    "ChaosConductor",
    "ChaosReport",
    "build_audit_context",
]

# The chaos conductor drives the routed serving fleet, so ``chaos.py``
# imports ``evox_tpu.service`` — which itself imports this package.  The
# names resolve lazily to break the cycle (and to keep ``import
# evox_tpu.resilience`` from dragging the whole serving stack in).
_CHAOS_EXPORTS = (
    "ChaosPlan",
    "ChaosConductor",
    "ChaosReport",
    "build_audit_context",
)


def __getattr__(name: str):
    if name in _CHAOS_EXPORTS:
        from . import chaos

        return getattr(chaos, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
