"""Global serving-stack invariants: the registry the chaos conductor audits.

Every durability and accounting promise the serving planes make — the
gateway's exactly-once admission, the journal-before-ack contract, the
router's single-writer placement discipline, bounded disk through tenant
churn, monotone fleet counters, SLO arithmetic — is stated here ONCE as a
pure checker over a plain :class:`AuditContext` snapshot, so the same
definition is enforced three ways:

* **continuously**, by :class:`~evox_tpu.resilience.chaos.ChaosConductor`
  against the live fleet between scheduling rounds;
* **at scale**, by ``tools/soak.py`` through the 100k-tenant churn ladder;
* **adversarially**, by the mutation tests (``tests/test_chaos.py``): for
  every registered invariant there is a seeded tampering — a torn ack, a
  double admit, an orphaned namespace, a deleted acked record — that MUST
  produce its violation, so a checker that silently rots fails the suite.

Checkers never raise on violation: they return structured
:class:`InvariantViolation` evidence (the conductor dumps each through the
:class:`~evox_tpu.obs.FlightRecorder` postmortem path), because a chaos
run's job is to *collect* every broken promise, not stop at the first.

Stdlib-only and side-effect free: a checker reads the snapshot it is
given.  Building the snapshot from a live fleet is the conductor's job
(``chaos.build_audit_context``).
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Mapping

__all__ = [
    "AuditContext",
    "InvariantViolation",
    "INVARIANTS",
    "audit_invariants",
]

#: Tolerance for SLO burn-rate arithmetic recomputation (pure float math
#: re-derived from the same integers; anything above rounding noise is an
#: accounting inconsistency, not imprecision).
_SLO_TOLERANCE = 1e-6


@dataclass
class InvariantViolation:
    """One broken promise, with the evidence to reproduce the verdict.

    :param invariant: registry key of the checker that fired.
    :param summary: one-line human statement of what broke.
    :param evidence: the snapshot slice the verdict was computed from —
        JSON-ready, dumped verbatim into the postmortem bundle manifest.
    :param round: the audit round the violation was detected at.
    """

    invariant: str
    summary: str
    evidence: dict[str, Any] = field(default_factory=dict)
    round: int = 0

    def to_json(self) -> dict[str, Any]:
        return asdict(self)


@dataclass
class AuditContext:
    """A plain snapshot of the whole-stack state one audit runs against.

    Every field defaults to empty so mutation tests can construct exactly
    the slice a checker reads — and tamper with it — without standing up
    a fleet.  The conductor fills all of them from the live system.
    """

    #: Audit round number (stamped into violations).
    round: int = 0
    #: Every ack the client plane received, in order:
    #: ``{"tenant_id", "uid", "kind" ("submit"/"steer"), "round"}``.
    acks: list[dict[str, Any]] = field(default_factory=list)
    #: The router journal, replayed to plain dicts:
    #: ``{"kind", "data": {...}}`` per record.
    router_records: list[dict[str, Any]] = field(default_factory=list)
    #: Each member's journal, replayed the same way, keyed by member index.
    member_records: dict[int, list[dict[str, Any]]] = field(
        default_factory=dict
    )
    #: The router's authoritative placement map:
    #: ``tenant_id -> {"member", "uid", ...}``.
    placements: dict[str, dict[str, Any]] = field(default_factory=dict)
    #: Tenants that have completed (results fetchable).
    completed: set[str] = field(default_factory=set)
    #: Tenants explicitly retired/forgotten — their disk must be GONE.
    forgotten: set[str] = field(default_factory=set)
    #: Member indices currently alive (not SIGKILLed, not retired).
    live_members: set[int] = field(default_factory=set)
    #: Tenant namespaces present on disk, keyed by member index.
    resident: dict[int, set[str]] = field(default_factory=dict)
    #: Monotone fleet counters, this audit and the previous one.
    counters: dict[str, float] = field(default_factory=dict)
    previous_counters: dict[str, float] = field(default_factory=dict)
    #: ``SLOTracker.describe()`` rows per scope (member index or "router").
    slo_reports: dict[str, list[dict[str, Any]]] = field(
        default_factory=dict
    )
    #: Journal growth per scope, and the compaction threshold that bounds
    #: it (``None`` = compaction unarmed for that scope: growth unchecked).
    records_since_snapshot: dict[str, int] = field(default_factory=dict)
    compact_records: dict[str, int | None] = field(default_factory=dict)
    #: Scopes whose journal has been compacted (a ``snapshot-anchor``
    #: record seen): per-record counting checks relax there — folded
    #: records are *gone by design*, not lost.
    compacted_scopes: set[str] = field(default_factory=set)


def _acked_submits(ctx: AuditContext) -> dict[str, list[dict[str, Any]]]:
    out: dict[str, list[dict[str, Any]]] = {}
    for ack in ctx.acks:
        if ack.get("kind") == "submit":
            out.setdefault(str(ack["tenant_id"]), []).append(ack)
    return out


def _journaled_tenants(ctx: AuditContext, *kinds: str) -> set[str]:
    return {
        str(rec.get("data", {}).get("tenant_id"))
        for rec in ctx.router_records
        if rec.get("kind") in kinds and rec.get("data", {}).get("tenant_id")
    }


def check_exactly_once_admission(
    ctx: AuditContext,
) -> list[InvariantViolation]:
    """Exactly-once admission under retries: every acked tenant has
    exactly one ``placement`` record in the router journal (migrations
    append ``migration`` records — identity moves, it is never re-minted)
    and at most one ``submit`` record in any one member journal."""
    violations: list[InvariantViolation] = []
    placement_counts: dict[str, int] = {}
    for rec in ctx.router_records:
        if rec.get("kind") == "placement":
            tid = str(rec.get("data", {}).get("tenant_id"))
            placement_counts[tid] = placement_counts.get(tid, 0) + 1
    for tid in sorted(_acked_submits(ctx)):
        n = placement_counts.get(tid, 0)
        # After router-journal compaction the original placement record is
        # folded into the snapshot (count 0 is legitimate); >1 is a
        # double-mint regardless.
        if n > 1 or (n == 0 and "router" not in ctx.compacted_scopes):
            violations.append(
                InvariantViolation(
                    "exactly-once-admission",
                    f"tenant {tid!r} was acked but has {n} placement "
                    f"record(s) in the router journal (exactly 1 required)",
                    {"tenant_id": tid, "placement_records": n},
                    ctx.round,
                )
            )
    for member, records in sorted(ctx.member_records.items()):
        submit_counts: dict[str, int] = {}
        for rec in records:
            if rec.get("kind") == "submit":
                tid = str(rec.get("data", {}).get("tenant_id"))
                submit_counts[tid] = submit_counts.get(tid, 0) + 1
        for tid, n in sorted(submit_counts.items()):
            if n > 1:
                violations.append(
                    InvariantViolation(
                        "exactly-once-admission",
                        f"member {member} journal holds {n} submit "
                        f"records for tenant {tid!r} (a retry was "
                        f"double-admitted)",
                        {"member": member, "tenant_id": tid, "submits": n},
                        ctx.round,
                    )
                )
    return violations


def check_reply_after_journal(ctx: AuditContext) -> list[InvariantViolation]:
    """Reply only after journal append: every ack the client plane holds
    from THIS round is cross-checked against a durable journal record —
    an ack without its record is a torn ack (the reply raced the fsync,
    the exact window journal-before-ack exists to close)."""
    violations: list[InvariantViolation] = []
    placed = _journaled_tenants(ctx, "placement", "migration")
    steered = _journaled_tenants(ctx, "steer")
    compacted = "router" in ctx.compacted_scopes
    if compacted:
        # Compaction folds records into the snapshot; the placement map
        # restored from it is the surviving durable evidence.
        placed |= set(ctx.placements)
    for ack in ctx.acks:
        if int(ack.get("round", -1)) != int(ctx.round):
            continue
        tid = str(ack["tenant_id"])
        kind = str(ack.get("kind", "submit"))
        if kind == "steer" and compacted:
            continue
        journaled = steered if kind == "steer" else placed
        if tid not in journaled:
            violations.append(
                InvariantViolation(
                    "reply-after-journal",
                    f"{kind} ack for tenant {tid!r} has no durable "
                    f"journal record backing it (torn ack)",
                    {"tenant_id": tid, "kind": kind},
                    ctx.round,
                )
            )
    return violations


def check_single_writer_per_namespace(
    ctx: AuditContext,
) -> list[InvariantViolation]:
    """Single writer per namespace: a tenant's checkpoint namespace is
    resident only on its placed member among LIVE members.  (A dead
    member's stale copy is legitimate migration residue; a live
    non-owner holding the namespace means two daemons could publish into
    one tenant's checkpoint chain.)"""
    violations: list[InvariantViolation] = []
    for tid, placement in sorted(ctx.placements.items()):
        owner = int(placement.get("member", -1))
        holders = sorted(
            member
            for member, tenants in ctx.resident.items()
            if tid in tenants and member in ctx.live_members
        )
        rogue = [m for m in holders if m != owner]
        if rogue:
            violations.append(
                InvariantViolation(
                    "single-writer-per-namespace",
                    f"tenant {tid!r} is placed on member {owner} but its "
                    f"namespace is resident on live member(s) {rogue} too",
                    {"tenant_id": tid, "owner": owner, "holders": holders},
                    ctx.round,
                )
            )
    return violations


def check_no_acked_record_lost(
    ctx: AuditContext,
) -> list[InvariantViolation]:
    """No acked record lost across restarts: every tenant whose submit
    was acked is still accounted for — placed, completed, or explicitly
    forgotten.  A tenant that vanished (its journal record deleted or
    dropped by a replay hole) is the one loss the whole journal
    discipline exists to prevent."""
    violations: list[InvariantViolation] = []
    for tid in sorted(_acked_submits(ctx)):
        if tid in ctx.forgotten:
            continue
        if tid not in ctx.placements and tid not in ctx.completed:
            violations.append(
                InvariantViolation(
                    "no-acked-record-lost",
                    f"tenant {tid!r} was acked but is neither placed, "
                    f"completed, nor forgotten (an acked record was lost)",
                    {"tenant_id": tid},
                    ctx.round,
                )
            )
    return violations


def check_bounded_disk(ctx: AuditContext) -> list[InvariantViolation]:
    """O(live-tenants) disk through churn: no orphaned tenant namespace
    (a directory for a tenant that is neither placed nor completed), no
    namespace surviving its tenant's retirement, and no journal growing
    unboundedly past its armed compaction threshold."""
    violations: list[InvariantViolation] = []
    retained = set(ctx.placements) | set(ctx.completed)
    for member, tenants in sorted(ctx.resident.items()):
        if member not in ctx.live_members:
            continue
        for tid in sorted(tenants):
            if tid in ctx.forgotten:
                violations.append(
                    InvariantViolation(
                        "bounded-disk",
                        f"tenant {tid!r} was forgotten but its namespace "
                        f"survives on member {member} (retention purge "
                        f"failed; disk grows O(ever-admitted))",
                        {"tenant_id": tid, "member": member},
                        ctx.round,
                    )
                )
            elif tid not in retained:
                violations.append(
                    InvariantViolation(
                        "bounded-disk",
                        f"orphaned namespace: member {member} holds a "
                        f"directory for tenant {tid!r}, which is neither "
                        f"placed nor completed",
                        {"tenant_id": tid, "member": member},
                        ctx.round,
                    )
                )
    for scope, since in sorted(ctx.records_since_snapshot.items()):
        threshold = ctx.compact_records.get(scope)
        if threshold is not None and since > 4 * int(threshold):
            violations.append(
                InvariantViolation(
                    "bounded-disk",
                    f"{scope} journal holds {since} records past its "
                    f"snapshot with compaction armed at {threshold} "
                    f"(recovery time is no longer bounded by cadence)",
                    {
                        "scope": scope,
                        "records_since_snapshot": since,
                        "compact_records": threshold,
                    },
                    ctx.round,
                )
            )
    return violations


def check_monotone_counters(ctx: AuditContext) -> list[InvariantViolation]:
    """Monotone fleet counters: a lifetime counter (submissions,
    completions, placements, rounds, injected events) that DECREASES
    between audits means a restart dropped journaled history or an
    accounting path double-books."""
    violations: list[InvariantViolation] = []
    for name, prev in sorted(ctx.previous_counters.items()):
        current = ctx.counters.get(name)
        if current is not None and float(current) < float(prev):
            violations.append(
                InvariantViolation(
                    "monotone-counters",
                    f"counter {name!r} decreased between audits "
                    f"({prev} -> {current})",
                    {"counter": name, "previous": prev, "current": current},
                    ctx.round,
                )
            )
    return violations


def check_slo_accounting(ctx: AuditContext) -> list[InvariantViolation]:
    """SLO-accounting consistency: every ``describe()`` row's published
    burn rate and budget remainder must re-derive from its own good/bad
    integers — ``burn = (bad/total)/error_budget``,
    ``budget_remaining = 1 - burn`` — and event counts must be
    non-negative.  A row that disagrees with its own arithmetic is
    corrupted accounting, however healthy it claims to be."""
    violations: list[InvariantViolation] = []
    for scope, rows in sorted(ctx.slo_reports.items()):
        for row in rows:
            try:
                good = float(row["good"])
                bad = float(row["bad"])
                target = float(row["target"])
                # burn_rate / budget_remaining are None while the rolling
                # window is empty — no evidence is not an inconsistency.
                burn = row["burn_rate"]
                remaining = row["budget_remaining"]
                if burn is not None:
                    burn = float(burn)
                if remaining is not None:
                    remaining = float(remaining)
            except (KeyError, TypeError, ValueError) as e:
                violations.append(
                    InvariantViolation(
                        "slo-accounting",
                        f"{scope} SLO row {row.get('slo')!r} is "
                        f"malformed ({type(e).__name__}: {e})",
                        {"scope": scope, "row": dict(row)},
                        ctx.round,
                    )
                )
                continue
            problems: list[str] = []
            if good < 0 or bad < 0:
                problems.append(f"negative event counts (good={good}, bad={bad})")
            total = good + bad
            error_budget = 1.0 - target
            if total > 0 and error_budget > 0:
                expected = (bad / total) / error_budget
                if burn is None or remaining is None:
                    problems.append(
                        f"window holds {int(total)} events but burn_rate/"
                        f"budget_remaining are unpublished (None)"
                    )
                else:
                    if abs(burn - expected) > _SLO_TOLERANCE:
                        problems.append(
                            f"burn_rate {burn} != (bad/total)/error_budget "
                            f"= {expected}"
                        )
                    if abs(remaining - (1.0 - expected)) > _SLO_TOLERANCE:
                        problems.append(
                            f"budget_remaining {remaining} != 1 - burn "
                            f"= {1.0 - expected}"
                        )
            for problem in problems:
                violations.append(
                    InvariantViolation(
                        "slo-accounting",
                        f"{scope} SLO row {row.get('slo')!r}: {problem}",
                        {"scope": scope, "row": dict(row)},
                        ctx.round,
                    )
                )
    return violations


#: The registry the conductor audits continuously — key is the violation's
#: ``invariant`` name; every entry has a mutation test proving it live.
INVARIANTS: dict[
    str, Callable[[AuditContext], list[InvariantViolation]]
] = {
    "exactly-once-admission": check_exactly_once_admission,
    "reply-after-journal": check_reply_after_journal,
    "single-writer-per-namespace": check_single_writer_per_namespace,
    "no-acked-record-lost": check_no_acked_record_lost,
    "bounded-disk": check_bounded_disk,
    "monotone-counters": check_monotone_counters,
    "slo-accounting": check_slo_accounting,
}


def audit_invariants(
    ctx: AuditContext,
    registry: Mapping[
        str, Callable[[AuditContext], list[InvariantViolation]]
    ] | None = None,
) -> list[InvariantViolation]:
    """Run every registered checker over one snapshot; returns the
    violations, in registry order (empty = every promise held)."""
    violations: list[InvariantViolation] = []
    for name, checker in (registry or INVARIANTS).items():
        found = checker(ctx)
        for violation in found:
            if violation.invariant != name:
                # A checker mis-labelling its own violations would break
                # the mutation tests' liveness proof — surface it.
                violation.evidence.setdefault("registered_as", name)
        violations.extend(found)
    return violations
