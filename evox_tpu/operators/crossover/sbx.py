"""Simulated binary crossover (SBX), full and half-offspring variants
(reference: ``src/evox/operators/crossover/sbx.py:4-39`` and
``sbx_half.py:4-35``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["simulated_binary", "simulated_binary_half"]


def _sbx_beta(key: jax.Array, shape, pro_c: float, dis_c: float, dtype) -> jax.Array:
    mu_key, dir_key, p1_key, p2_key = jax.random.split(key, 4)
    mu = jax.random.uniform(mu_key, shape, dtype=dtype)
    beta = jnp.where(
        mu <= 0.5,
        (2.0 * mu) ** (1.0 / (dis_c + 1.0)),
        (2.0 - 2.0 * mu) ** (-1.0 / (dis_c + 1.0)),
    )
    # Random contraction/expansion direction per gene.
    sign = 1 - 2 * jax.random.randint(dir_key, shape, 0, 2)
    beta = beta * sign
    # Half the genes (and all genes of non-crossover pairs) pass through.
    beta = jnp.where(jax.random.uniform(p1_key, shape, dtype=dtype) < 0.5, 1.0, beta)
    beta = jnp.where(jax.random.uniform(p2_key, shape, dtype=dtype) > pro_c, 1.0, beta)
    return beta


def simulated_binary(
    key: jax.Array, x: jax.Array, pro_c: float = 1.0, dis_c: float = 20.0
) -> jax.Array:
    """SBX producing a full set of offspring (two per parent pair).

    :param x: parents, (n, d); pairs are (x[i], x[i + n//2]).
    :return: (2 * (n // 2), d) offspring.
    """
    n, d = x.shape
    p1 = x[: n // 2]
    p2 = x[n // 2 : n // 2 * 2]
    beta = _sbx_beta(key, p1.shape, pro_c, dis_c, x.dtype)
    mean = (p1 + p2) / 2.0
    diff = beta * (p1 - p2) / 2.0
    return jnp.concatenate([mean + diff, mean - diff], axis=0)


def simulated_binary_half(
    key: jax.Array, x: jax.Array, pro_c: float = 1.0, dis_c: float = 20.0
) -> jax.Array:
    """SBX producing one offspring per parent pair ((n // 2, d))."""
    n, d = x.shape
    p1 = x[: n // 2]
    p2 = x[n // 2 : n // 2 * 2]
    beta = _sbx_beta(key, p1.shape, pro_c, dis_c, x.dtype)
    return (p1 + p2) / 2.0 + beta * (p1 - p2) / 2.0
