"""Differential-evolution crossover family.

TPU-native counterpart of the reference
(``src/evox/operators/crossover/differential_evolution.py:8-96``): padded
difference-vector sums (replacement-sampled indices) and binary / exponential
/ arithmetic recombination, all fixed-shape whole-population ops.

Deviation noted for parity review: the reference's binary crossover draws the
per-gene mask from a *normal* distribution (``torch.randn < CR``,
``differential_evolution.py:55``); standard DE (and this implementation) uses
a uniform draw, which makes ``CR`` the actual crossover probability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "DE_differential_sum",
    "DE_binary_crossover",
    "DE_exponential_crossover",
    "DE_arithmetic_recombination",
]


def DE_differential_sum(
    key: jax.Array,
    diff_padding_num: int,
    num_diff_vectors: jax.Array,
    index: jax.Array,
    population: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Sum of ``num_diff_vectors`` random difference vectors per individual,
    computed over a fixed ``diff_padding_num``-wide padded index table so the
    shape is static regardless of the (possibly per-individual, traced)
    number of difference vectors.

    :param key: PRNG key.
    :param diff_padding_num: static max number of sampled indices.
    :param num_diff_vectors: scalar or (pop_size,) number of difference pairs.
    :param index: (pop_size,) index of each current individual.
    :param population: (pop_size, dim).
    :return: ``(difference_sum, first_rand_index)``.
    """
    pop_size = population.shape[0]
    # scalar -> (1, 1) broadcast over the population; per-individual -> (n, 1)
    select_len = jnp.reshape(jnp.atleast_1d(num_diff_vectors) * 2 + 1, (-1, 1))

    rand_indices = jax.random.randint(
        key, (pop_size, diff_padding_num), 0, pop_size
    )
    rand_indices = jnp.where(
        rand_indices == index[:, None], pop_size - 1, rand_indices
    )

    pop_permute = population[rand_indices]  # (n, pad, dim)
    mask = jnp.arange(diff_padding_num)[None, :] < select_len
    pop_padded = jnp.where(mask[:, :, None], pop_permute, 0.0)

    diff_vectors = pop_padded[:, 1:]
    difference_sum = jnp.sum(diff_vectors[:, 0::2], axis=1) - jnp.sum(
        diff_vectors[:, 1::2], axis=1
    )
    return difference_sum, rand_indices[:, 0]


def DE_binary_crossover(
    key: jax.Array,
    mutation_vector: jax.Array,
    current_vector: jax.Array,
    CR: jax.Array,
) -> jax.Array:
    """Binomial crossover: each gene comes from the mutant with probability
    ``CR``; one random gene per individual is always taken from the mutant."""
    pop_size, dim = mutation_vector.shape
    CR = jnp.asarray(CR)
    if CR.ndim == 1:
        CR = CR[:, None]
    mask_key, j_key = jax.random.split(key)
    mask = jax.random.uniform(mask_key, (pop_size, dim)) < CR
    rind = jax.random.randint(j_key, (pop_size,), 0, dim)[:, None]
    jind = jnp.arange(dim)[None, :] == rind
    return jnp.where(mask | jind, mutation_vector, current_vector)


def DE_exponential_crossover(
    key: jax.Array,
    mutation_vector: jax.Array,
    current_vector: jax.Array,
    CR: jax.Array,
) -> jax.Array:
    """Exponential crossover: a contiguous (wrapping) segment of
    geometrically-distributed length starting at a random gene comes from the
    mutant (reference ``differential_evolution.py:61-83``)."""
    pop_size, dim = mutation_vector.shape
    CR = jnp.asarray(CR)
    n_key, l_key = jax.random.split(key)
    start = jax.random.randint(n_key, (pop_size,), 0, dim)
    tiny = jnp.finfo(jnp.float32).tiny
    u = jnp.clip(jax.random.uniform(l_key, (pop_size,)), tiny, None)
    # Geometric segment length via inverse-CDF, as in the reference.
    seg_len = jnp.floor(jnp.log(u) / (-jnp.log1p(CR))).astype(jnp.int32)
    length = jnp.minimum(seg_len, dim) - 1
    base_mask = jnp.arange(dim)[None, :] < length[:, None]
    tiled = jnp.tile(base_mask, (1, 2))
    cols = start[:, None] + jnp.arange(dim)[None, :]
    mask = jnp.take_along_axis(tiled, cols, axis=1)
    return jnp.where(mask, mutation_vector, current_vector)


def DE_arithmetic_recombination(
    mutation_vector: jax.Array, current_vector: jax.Array, K: jax.Array
) -> jax.Array:
    """Arithmetic recombination: ``x + K * (v - x)``."""
    K = jnp.asarray(K)
    if K.ndim == 1:
        K = K[:, None]
    return current_vector + K * (mutation_vector - current_vector)
