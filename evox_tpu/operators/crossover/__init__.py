"""Crossover operators (reference ``src/evox/operators/crossover/``):
SBX full/half and the DE recombination family - pure tensor->tensor
functions over whole populations.
"""

__all__ = [
    "DE_differential_sum",
    "DE_exponential_crossover",
    "DE_binary_crossover",
    "DE_arithmetic_recombination",
    "simulated_binary",
    "simulated_binary_half",
]

from .differential_evolution import (
    DE_arithmetic_recombination,
    DE_binary_crossover,
    DE_differential_sum,
    DE_exponential_crossover,
)
from .sbx import simulated_binary, simulated_binary_half
