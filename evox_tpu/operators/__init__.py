"""Operator layer: pure tensor->tensor functions with explicit PRNG keys
(reference: ``src/evox/operators/__init__.py:1-4``)."""

__all__ = ["crossover", "mutation", "sampling", "selection", "crowding_distance", "non_dominate_rank"]

from . import crossover, mutation, sampling, selection
from .selection import crowding_distance, non_dominate_rank
