"""Das-Dennis simplex-lattice reference-vector sampling for MOEAs
(reference: ``src/evox/operators/sampling/uniform.py:8-51``).  Host-side
(itertools) construction, exactly like the reference — reference vectors are
computed once at algorithm setup, never inside the jitted loop."""

from __future__ import annotations

import itertools
from math import comb

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["uniform_sampling"]


def _das_dennis_layer(h: int, m: int) -> np.ndarray:
    combos = np.asarray(
        list(itertools.combinations(range(1, h + m), m - 1)), dtype=np.float64
    )
    combos = combos - np.arange(m - 1)[None, :] - 1
    left = np.concatenate([combos, np.full((combos.shape[0], 1), h)], axis=1)
    right = np.concatenate([np.zeros((combos.shape[0], 1)), combos], axis=1)
    return (left - right) / h


def uniform_sampling(n: int, m: int) -> tuple[jax.Array, int]:
    """Generate ~``n`` uniformly spread points on the ``m``-simplex (Das and
    Dennis's method, with Deb and Jain's inner-layer augmentation when the
    boundary layer is too coarse).

    :return: ``(points, n_samples)``; points have shape ``(n_samples, m)``.
    """
    h1 = 1
    while comb(h1 + m, m - 1) <= n:
        h1 += 1
    w = _das_dennis_layer(h1, m)

    if h1 < m:
        h2 = 0
        while comb(h1 + m - 1, m - 1) + comb(h2 + m, m - 1) <= n:
            h2 += 1
        if h2 > 0:
            w2 = _das_dennis_layer(h2, m)
            w = np.concatenate([w, w2 / 2.0 + 1.0 / (2.0 * m)], axis=0)

    w = np.maximum(w, 1e-6)
    return jnp.asarray(w, dtype=jnp.float32), w.shape[0]
