"""Grid (meshgrid lattice) sampling (reference:
``src/evox/operators/sampling/gird.py:7-32`` — the reference file name is a
typo kept out of this tree; the module is re-exported under both names)."""

from __future__ import annotations

from math import ceil

import jax
import jax.numpy as jnp

__all__ = ["grid_sampling"]


def grid_sampling(n: int, m: int) -> tuple[jax.Array, int]:
    """Uniform lattice of ~``n`` points in the unit hypercube ``[0, 1]^m``.

    :return: ``(points, n_samples)`` with ``n_samples = ceil(n^(1/m))^m``.
    """
    num_points = int(ceil(n ** (1 / m)))
    gap = jnp.linspace(0.0, 1.0, num_points)
    grid = jnp.meshgrid(*([gap] * m), indexing="ij")
    w = jnp.stack(grid, axis=-1).reshape(-1, m)
    w = w[:, ::-1]
    return w, w.shape[0]
