"""Latin hypercube sampling (reference:
``src/evox/operators/sampling/latin_hypercube.py:4-38``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["latin_hypercube_sampling", "latin_hypercube_sampling_standard"]


def latin_hypercube_sampling_standard(
    key: jax.Array, n: int, d: int, smooth: bool = True
) -> jax.Array:
    """LHS in the unit hypercube: one sample per stratum per dimension, with
    independently permuted strata across dimensions.

    :return: (n, d) samples.
    """
    perm_key, jitter_key = jax.random.split(key)
    # Independent permutation of the n strata in each of the d columns.
    cells = jnp.argsort(jax.random.uniform(perm_key, (n, d)), axis=0).astype(
        jnp.float32
    )
    if smooth:
        offset = jax.random.uniform(jitter_key, (n, d))
    else:
        offset = 0.5
    return (cells + offset) / n


def latin_hypercube_sampling(
    key: jax.Array, n: int, lb: jax.Array, ub: jax.Array, smooth: bool = True
) -> jax.Array:
    """LHS in the box ``[lb, ub]`` (both 1-D of size ``d``)."""
    assert lb.ndim == 1 and ub.ndim == 1 and lb.shape == ub.shape
    samples = latin_hypercube_sampling_standard(key, n, lb.shape[0], smooth)
    return lb[None, :] + samples.astype(lb.dtype) * (ub - lb)[None, :]
