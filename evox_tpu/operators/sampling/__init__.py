"""Sampling operators (reference ``src/evox/operators/sampling/``):
Das-Dennis simplex lattices, Latin hypercube, and grid sampling.
"""

__all__ = [
    "grid_sampling",
    "latin_hypercube_sampling",
    "latin_hypercube_sampling_standard",
    "uniform_sampling",
]

from .grid import grid_sampling
from .latin_hypercube import latin_hypercube_sampling, latin_hypercube_sampling_standard
from .uniform import uniform_sampling
