__all__ = ["polynomial_mutation"]

from .pm_mutation import polynomial_mutation
