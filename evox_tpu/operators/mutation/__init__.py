"""Mutation operators (reference ``src/evox/operators/mutation/``):
PlatEMO-style polynomial mutation over whole populations.
"""

__all__ = ["polynomial_mutation"]

from .pm_mutation import polynomial_mutation
