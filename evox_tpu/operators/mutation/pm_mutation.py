"""Polynomial mutation (PlatEMO-style; reference:
``src/evox/operators/mutation/pm_mutation.py:6-68``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["polynomial_mutation"]


def polynomial_mutation(
    key: jax.Array,
    x: jax.Array,
    lb: jax.Array,
    ub: jax.Array,
    pro_m: float = 1.0,
    dis_m: float = 20.0,
) -> jax.Array:
    """Polynomial mutation: each gene mutates with probability ``pro_m / d``
    using a polynomial perturbation with distribution index ``dis_m``.

    :param x: population (n, d); ``lb``/``ub`` broadcastable bounds.
    :return: mutated population (n, d), clipped to bounds.
    """
    n, d = x.shape
    site_key, mu_key = jax.random.split(key)
    site = jax.random.uniform(site_key, (n, d), dtype=x.dtype) < pro_m / d
    mu = jax.random.uniform(mu_key, (n, d), dtype=x.dtype)

    pop = jnp.clip(x, lb, ub)
    span = ub - lb

    # mu <= 0.5: perturb toward the lower bound.
    low = site & (mu <= 0.5)
    norm_l = jnp.where(low, (pop - lb) / span, 0.0)
    delta_l = (2.0 * mu + (1.0 - 2.0 * mu) * (1.0 - norm_l) ** (dis_m + 1.0)) ** (
        1.0 / (dis_m + 1.0)
    ) - 1.0
    pop = jnp.where(low, pop + span * delta_l, pop)

    # mu > 0.5: perturb toward the upper bound.
    high = site & (mu > 0.5)
    norm_h = jnp.where(high, (ub - pop) / span, 0.0)
    delta_h = 1.0 - (
        2.0 * (1.0 - mu) + 2.0 * (mu - 0.5) * (1.0 - norm_h) ** (dis_m + 1.0)
    ) ** (1.0 / (dis_m + 1.0))
    pop = jnp.where(high, pop + span * delta_h, pop)
    return pop
