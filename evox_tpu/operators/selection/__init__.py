"""Selection operators (reference ``src/evox/operators/selection/``):
non-dominated sorting, crowding distance, RVEA reference-vector
selection, tournaments, and p-best picks.
"""

__all__ = [
    "crowding_distance",
    "nd_environmental_selection",
    "non_dominate_rank",
    "dominate_relation",
    "ref_vec_guided",
    "select_rand_pbest",
    "tournament_selection",
    "tournament_selection_multifit",
]

from .find_pbest import select_rand_pbest
from .non_dominate import (
    crowding_distance,
    dominate_relation,
    nd_environmental_selection,
    non_dominate_rank,
)
from .rvea_selection import ref_vec_guided
from .tournament_selection import tournament_selection, tournament_selection_multifit
