"""Reference-vector guided (RVEA) survivor selection.

TPU-native counterpart of the reference
(``src/evox/operators/selection/rvea_selection.py:7-99``): for each reference
vector, pick the associated solution with minimal angle-penalized distance
(APD).  Output is NaN-padded to the fixed reference-vector count — the
fixed-shape idiom the reference uses to keep a "variable-size" population
compile-friendly (SURVEY hard-part №2); downstream RVEA steps treat NaN rows
as empty slots.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["ref_vec_guided", "apd_fn"]


def _cosine_similarity(a: jax.Array, b: jax.Array) -> jax.Array:
    """Pairwise cosine similarity between rows of ``a`` (n, m) and ``b`` (r, m)
    — one (n, m) x (m, r) MXU matmul plus norm scaling."""
    a_n = a / jnp.maximum(jnp.linalg.norm(a, axis=-1, keepdims=True), 1e-12)
    b_n = b / jnp.maximum(jnp.linalg.norm(b, axis=-1, keepdims=True), 1e-12)
    return a_n @ b_n.T


def apd_fn(
    partition: jax.Array,
    gamma: jax.Array,
    angle: jax.Array,
    obj: jax.Array,
    theta: jax.Array,
) -> jax.Array:
    """Angle-penalized distance for each (solution, reference-vector) slot
    (reference ``rvea_selection.py:7-29``)."""
    m = obj.shape[1]
    selected_angle = jnp.take_along_axis(angle, jnp.maximum(partition, 0), axis=0)
    left = (1 + m * theta * selected_angle) / gamma[None, :]
    norm_obj = jnp.linalg.norm(obj, axis=1)
    right = norm_obj[partition]
    return left * right


def ref_vec_guided(
    x: jax.Array, f: jax.Array, v: jax.Array, theta: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """RVEA selection: returns ``(next_x, next_f)`` of shape ``(r, ·)`` where
    reference vectors with no associated solution yield NaN rows."""
    n = f.shape[0]
    nv = v.shape[0]

    obj = f - jnp.nanmin(f, axis=0, keepdims=True)
    obj = jnp.maximum(obj, 1e-32)

    # Acute angle of each reference vector to its nearest neighbor.
    vv = _cosine_similarity(v, v)
    vv = jnp.where(jnp.eye(nv, dtype=bool), 0.0, vv)
    vv = jnp.clip(vv, 0.0, 1.0)
    gamma = jnp.min(jnp.arccos(vv), axis=1)

    # Angle of each solution to each reference vector.
    angle = jnp.arccos(jnp.clip(_cosine_similarity(obj, v), 0.0, 1.0))

    nan_mask = jnp.isnan(obj).any(axis=1)
    associate = jnp.argmin(angle, axis=1)
    associate = jnp.where(nan_mask, -1, associate)

    idx_v = jnp.arange(nv)[None, :]
    assoc_col = associate[:, None]
    partition = jnp.where(
        assoc_col == idx_v, jnp.arange(n)[:, None], -1
    )  # (n, nv): row index of solutions associated to each vector, else -1

    mask = assoc_col != idx_v
    mask_null = jnp.sum(mask, axis=0) == n  # vectors with no associated solution

    apd = apd_fn(partition, gamma, angle, obj, theta)
    apd = jnp.where(mask, jnp.inf, apd)

    next_ind = jnp.argmin(apd, axis=0)
    next_x = jnp.where(mask_null[:, None], jnp.nan, x[next_ind])
    next_f = jnp.where(mask_null[:, None], jnp.nan, f[next_ind])
    return next_x, next_f
