"""Reference-vector guided (RVEA) survivor selection.

TPU-native counterpart of the reference
(``src/evox/operators/selection/rvea_selection.py:7-99``): for each reference
vector, pick the associated solution with minimal angle-penalized distance
(APD).  Output is NaN-padded to the fixed reference-vector count — the
fixed-shape idiom the reference uses to keep a "variable-size" population
compile-friendly (SURVEY hard-part №2); downstream RVEA steps treat NaN rows
as empty slots.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["ref_vec_guided", "apd_fn"]


def _cosine_similarity(a: jax.Array, b: jax.Array) -> jax.Array:
    """Pairwise cosine similarity between rows of ``a`` (n, m) and ``b`` (r, m)
    — one (n, m) x (m, r) MXU matmul plus norm scaling."""
    a_n = a / jnp.maximum(jnp.linalg.norm(a, axis=-1, keepdims=True), 1e-12)
    b_n = b / jnp.maximum(jnp.linalg.norm(b, axis=-1, keepdims=True), 1e-12)
    return a_n @ b_n.T


def apd_fn(
    partition: jax.Array,
    gamma: jax.Array,
    angle: jax.Array,
    obj: jax.Array,
    theta: jax.Array,
) -> jax.Array:
    """Angle-penalized distance for each (solution, reference-vector) slot
    (reference ``rvea_selection.py:7-29``)."""
    m = obj.shape[1]
    selected_angle = jnp.take_along_axis(angle, jnp.maximum(partition, 0), axis=0)
    left = (1 + m * theta * selected_angle) / gamma[None, :]
    norm_obj = jnp.linalg.norm(obj, axis=1)
    right = norm_obj[partition]
    return left * right


def ref_vec_guided(
    x: jax.Array, f: jax.Array, v: jax.Array, theta: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """RVEA selection: returns ``(next_x, next_f)`` of shape ``(r, ·)`` where
    reference vectors with no associated solution yield NaN rows.

    TPU shape: the reference materializes the full ``(n, r)`` APD matrix and
    gathers through an ``(n, r)`` partition table (``rvea_selection.py:
    59-99``), which on TPU means two ~``n*r``-element gathers (measured at
    0.25 gen/s for pop=10k).  But APD is only ever *compared within one
    reference-vector group* (everything else is masked to +inf), and the
    per-group ``gamma[j]`` divisor is a positive constant that cannot change
    the within-group ranking — so the survivor of group ``j`` is just the
    segment-argmin of ``(1 + m·theta·angle_to_own_vector) * ||obj||`` over
    the solutions associated with ``j``.  The ``(n, r)`` cosine matrix is
    consumed by two row reductions straight out of the MXU matmul and never
    re-indexed; survivor extraction is two O(n) scatter-mins."""
    n = f.shape[0]
    nv = v.shape[0]
    m = f.shape[1]

    obj = f - jnp.nanmin(f, axis=0, keepdims=True)
    obj = jnp.maximum(obj, 1e-32)

    # The reference's gamma (nearest-neighbor angle per reference vector,
    # ``rvea_selection.py:60-66``) divides every APD in group j by the same
    # positive constant — ranking-neutral, so it is not computed at all
    # (``apd_fn`` above keeps the full formula for callers that want it).

    # Associate each solution with its min-angle (max-cosine) vector; the
    # only angle APD ever uses is the one to the solution's own vector.
    cos = jnp.clip(_cosine_similarity(obj, v), 0.0, 1.0)
    associate = jnp.argmax(cos, axis=1)
    own_angle = jnp.arccos(jnp.max(cos, axis=1))

    # Non-finite rows (NaN empty slots, or inf fitness from an overflowing
    # evaluate) are never candidates: their cosine row is all-NaN, which
    # would otherwise route through argmax to group 0 and poison its
    # scatter-min.
    nan_mask = ~jnp.isfinite(f).all(axis=1)
    vals = (1.0 + m * theta * own_angle) * jnp.linalg.norm(obj, axis=1)
    vals = jnp.where(nan_mask, jnp.inf, vals)
    # NaN rows associate with no vector: scatter them out of bounds (dropped).
    scatter_idx = jnp.where(nan_mask, nv, associate)

    best = jnp.full((nv,), jnp.inf, vals.dtype).at[scatter_idx].min(
        vals, mode="drop"
    )
    # Tie-break equal APD at the lowest solution index (the dense argmin's
    # first-occurrence rule).
    is_best = (vals == best[jnp.where(nan_mask, 0, associate)]) & ~nan_mask
    cand = jnp.where(is_best, jnp.arange(n), n)
    next_ind = jnp.full((nv,), n, cand.dtype).at[scatter_idx].min(
        cand, mode="drop"
    )

    mask_null = ~jnp.isfinite(best)  # vectors with no associated solution
    next_ind = jnp.minimum(next_ind, n - 1)
    next_x = jnp.where(mask_null[:, None], jnp.nan, x[next_ind])
    next_f = jnp.where(mask_null[:, None], jnp.nan, f[next_ind])
    return next_x, next_f
