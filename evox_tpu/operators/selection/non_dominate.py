"""Non-dominated sorting, crowding distance, NSGA-II environmental selection.

TPU-native counterpart of the reference
(``src/evox/operators/selection/non_dominate.py:6-262``).  The reference needs
a custom-op registration with two hand-written vmap levels to make the
Pareto-front peeling loop survive ``torch.compile`` + nested ``vmap``
(``non_dominate.py:155-157``); in JAX a single ``lax.while_loop`` with
fixed-shape carries is natively jittable *and* vmappable (batched while_loop
runs until all batch members converge), so no registration machinery exists.

The O(n²m) dominance matrix is the hot spot for large populations (SURVEY
§2.3 ⚠); ``evox_tpu.ops.dominance`` provides a Pallas blocked kernel used
automatically above a size threshold when the ``EVOX_TPU_PALLAS`` runtime
gate is open (see ``evox_tpu.ops.pallas_gate``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...utils import lexsort

__all__ = [
    "dominate_relation",
    "non_dominate_rank",
    "crowding_distance",
    "nd_environmental_selection",
]


def dominate_relation(x: jax.Array, y: jax.Array) -> jax.Array:
    """Boolean matrix ``A[i, j] = x_i dominates y_j`` (all objectives <=, at
    least one <)."""
    le = jnp.all(x[:, None, :] <= y[None, :, :], axis=-1)
    lt = jnp.any(x[:, None, :] < y[None, :, :], axis=-1)
    return le & lt


def non_dominate_rank(f: jax.Array, until_count: int | None = None) -> jax.Array:
    """Non-domination rank of each row of ``f`` (n, m): rank 0 = Pareto front,
    rank 1 = front after removing rank 0, etc.

    Iterative front peeling with a ``lax.while_loop`` over fixed-shape
    carries — the JAX equivalent of the reference's compiled
    ``torch.while_loop`` path (``non_dominate.py:130-148``).

    :param until_count: when set (static), peeling stops once at least
        this many rows have been ranked (always after a *whole* front).
        Unranked rows get the sentinel rank ``n`` — larger than any real
        rank, so order-by-rank semantics are preserved for every ranked
        row.  Survivor selection of ``k`` of ``n`` rows only needs ranks
        up to the front crossing ``k`` (typically ~half the fronts when
        k = n/2), which halves the peeling loop's matrix traffic; exact
        full ranking remains the default.

    Above ``EVOX_TPU_PACKED_RANK_MIN_POP`` rows (default 2048) the
    dominance matrix is **bit-packed** (:func:`_non_dominate_rank_packed`):
    32 dominator rows per uint32 word, peels via
    ``lax.population_count`` — 8× less HBM traffic per peel than the
    1-byte bool matrix the peeling loop re-reads every front, and 32×
    less resident matrix memory (at n=100k the bool matrix would be
    10 GB; packed is 1.25 GB).  Ranks are identical; both paths are
    jit/vmap-compatible.
    """
    n = f.shape[0]
    if f.ndim == 2 and n >= _packed_rank_min_pop():
        # The (gated) Pallas kernel path keeps the unpacked loop: it
        # produces the bool matrix in VMEM tiles, and re-packing it would
        # re-materialize exactly the traffic it saves.  Mirror
        # ``_dominance_matrix``'s dispatch exactly (including its
        # f64-on-TPU exclusion) so "gate open but kernel ineligible"
        # still takes the packed path, not the dense broadcast.
        if not _pallas_kernel_eligible(f):
            return _non_dominate_rank_packed(f, until_count)
    dom = _dominance_matrix(f)
    dominate_count = jnp.sum(dom, axis=0, dtype=jnp.int32)

    def count_desc_fn(pf):
        # Dominance contributions of the peeled front.
        return jnp.sum(pf[:, None] * dom, axis=0, dtype=jnp.int32)

    return _peel_fronts(dominate_count, count_desc_fn, n, until_count)


def _peel_fronts(
    dominate_count: jax.Array, count_desc_fn, n: int, until_count: int | None
) -> jax.Array:
    """The shared peeling loop over a dominate-count vector.  Unranked rows
    (only possible with ``until_count``) keep the sentinel rank ``n``."""
    rank = jnp.full((n,), n, dtype=jnp.int32)
    pareto_front = dominate_count == 0

    def cond_fn(carry):
        _, _, _, pf, assigned = carry
        more = jnp.any(pf)
        if until_count is not None:
            more = more & (assigned < until_count)
        return more

    def body_fn(carry):
        rank, current_rank, dc, pf, assigned = carry
        rank = jnp.where(pf, current_rank, rank)
        assigned = assigned + jnp.sum(pf, dtype=jnp.int32)
        dc = dc - count_desc_fn(pf) - pf.astype(jnp.int32)
        return rank, current_rank + 1, dc, dc == 0, assigned

    rank, *_ = jax.lax.while_loop(
        cond_fn,
        body_fn,
        (rank, jnp.int32(0), dominate_count, pareto_front, jnp.int32(0)),
    )
    return rank


def _packed_rank_min_pop() -> int:
    import os

    return int(os.environ.get("EVOX_TPU_PACKED_RANK_MIN_POP", "2048"))


def _pack_bits(rows: jax.Array) -> jax.Array:
    """Pack a (32, n) bool block into an (n,) uint32 word (bit b = row b)."""
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[:, None]
    return jnp.sum(rows.astype(jnp.uint32) * weights, axis=0)


def _non_dominate_rank_packed(
    f: jax.Array, until_count: int | None = None
) -> jax.Array:
    """Front peeling on a bit-packed dominance matrix.

    The packed matrix ``packed[w, j]`` holds, in bit ``b``, whether row
    ``32w+b`` dominates row ``j``.  It is built 32 dominator rows at a
    time with ``lax.map`` — the (32, n, m) broadcast compare stays in
    registers/VMEM under fusion, so the full (n, n) bool matrix is never
    materialized in HBM.  Each peel is then
    ``count_desc[j] = Σ_w popcount(packed[w, j] & pf_mask[w])`` — the
    same arithmetic the unpacked loop does, at 1/8 the bytes.
    """
    n, m = f.shape
    nw = -(-n // 32)  # words of 32 dominator rows
    pad = nw * 32 - n

    # Padded copy whose extra rows dominate nothing and are dominated by
    # everything real (all-inf objectives): their packed bits stay 0.
    fp = jnp.pad(f, ((0, pad), (0, 0)), constant_values=jnp.inf)

    def pack_word(w):
        block = jax.lax.dynamic_slice_in_dim(fp, w * 32, 32)  # (32, m)
        return _pack_bits(dominate_relation(block, f))  # (n,)

    # batch_size vectorizes 8 words (256 dominator rows) per scan step:
    # fewer, larger fused blocks for the TPU without materializing the
    # full matrix (CPU-measured neutral, see BASELINE.md).
    packed = jax.lax.map(pack_word, jnp.arange(nw), batch_size=8)  # (nw, n) uint32

    popcount = jax.lax.population_count
    dominate_count = jnp.sum(popcount(packed), axis=0, dtype=jnp.int32)

    def count_desc_fn(pf):
        pf_mask = _pack_bits(
            jnp.pad(pf, (0, pad)).reshape(nw, 32).T
        )  # (nw,) uint32
        return jnp.sum(
            popcount(packed & pf_mask[:, None]), axis=0, dtype=jnp.int32
        )

    return _peel_fronts(dominate_count, count_desc_fn, n, until_count)


_PALLAS_MIN_POP_DEFAULT = 4096


def _dominance_matrix(f: jax.Array) -> jax.Array:
    """Dominance matrix: XLA's fused broadcast-compare by default; the Pallas
    blocked-tile kernel (``evox_tpu.ops.dominance``) when the runtime gate is
    open and the population is large enough for tiling to pay.

    The gate (``evox_tpu.ops.pallas_gate``, ``EVOX_TPU_PALLAS`` env var with
    a one-shot subprocess capability probe) exists because Pallas/Mosaic is
    not supported on every TPU attachment — a ``pallas_call`` over this
    box's remote tunnel hung the single-client relay for >15 min — so the
    kernel must never dispatch unless the attachment is known-good.  Below
    ``EVOX_TPU_PALLAS_MIN_POP`` (default 4096) the broadcast path wins on
    fusion alone and is always used.

    float64 objectives (``jax_enable_x64``) on a real TPU stay on the
    broadcast path even when the gate is open: Mosaic has no f64 tile
    compare, so dispatching the kernel would fail at compile time rather
    than fall back (and downcasting inside the kernel could rank
    differently from the XLA path)."""
    if _pallas_kernel_eligible(f):
        from ...ops.dominance import dominance_matrix

        return dominance_matrix(f)
    return dominate_relation(f, f)


def _pallas_kernel_eligible(f: jax.Array) -> bool:
    """Would ``_dominance_matrix`` dispatch the Pallas kernel for ``f``?
    One predicate shared by the matrix and rank dispatchers so their
    routing can never disagree.

    **Demoted (PR 15):** the dominance kernel measurably LOSES to plain
    XLA on the NSGA-II bench (69 vs 90 gen/s; the packed broadcast path
    fuses better) — the general ``EVOX_TPU_PALLAS`` gate alone no longer
    dispatches it anywhere.  It is kept as an explicit opt-in
    (``EVOX_TPU_PALLAS_DOMINANCE=1`` *in addition to* the open gate) with
    its bench twin (``nsga2_dtlz2_pallas``) recording the loss, so the
    next TPU sweep can re-litigate the verdict empirically instead of
    the kernel rotting as silent dead code.  Pallas effort now aims at
    the ops where XLA demonstrably loses: the tiled crowding-distance
    kernel (``ops/crowding.py``) and the masked top-k selection kernel
    (``ops/topk.py``)."""
    import os

    if os.environ.get("EVOX_TPU_PALLAS_DOMINANCE", "0").strip().lower() not in (
        "1",
        "force",
        "on",
        "true",
    ):
        return False
    return _pallas_op_eligible(
        f, 2, "EVOX_TPU_PALLAS_MIN_POP", default_min_pop=_PALLAS_MIN_POP_DEFAULT
    )


def _pallas_op_eligible(
    arr: jax.Array, ndim: int, min_pop_env: str, default_min_pop: int = 8192
) -> bool:
    """ONE definition of the per-op Pallas gating shape, so the three
    dispatchers can never drift: input rank and dispatch threshold
    (``min_pop_env`` rows, env-overridable), no f64 on a real TPU (Mosaic
    has no f64 tile compare — dispatching would fail at compile time
    instead of falling back), and the capability gate itself
    (:mod:`evox_tpu.ops.pallas_gate`)."""
    import os

    min_pop = int(os.environ.get(min_pop_env, str(default_min_pop)))
    if arr.ndim != ndim or arr.shape[0] < min_pop:
        return False
    if arr.dtype == jnp.float64 and jax.default_backend() == "tpu":
        return False
    from ...ops.pallas_gate import pallas_enabled

    return pallas_enabled()


def _pallas_crowding_eligible(costs: jax.Array) -> bool:
    """Route ``crowding_distance`` to the tiled neighbor kernel
    (``ops/crowding.py``)?  Unlike the demoted dominance kernel, this one
    targets an op XLA demonstrably loses on (the pop=50k NSGA-II
    sort+scatter cliff), so the open gate alone dispatches it.  The
    ``crowding_50k[_pallas]`` bench twins record whether it actually wins
    per attachment."""
    return _pallas_op_eligible(costs, 2, "EVOX_TPU_PALLAS_CROWDING_MIN_POP")


def _pallas_topk_eligible(values: jax.Array) -> bool:
    """Route the survivor-selection rank threshold to the masked top-k
    rank-by-count kernel (``ops/topk.py``)?  Same gating shape as
    crowding; the ``topk_50k[_pallas]`` bench twins record the verdict."""
    return _pallas_op_eligible(values, 1, "EVOX_TPU_PALLAS_TOPK_MIN_POP")


def crowding_distance(costs: jax.Array, mask: jax.Array | None = None) -> jax.Array:
    """NSGA-II crowding distance over the ``mask``-selected rows of ``costs``
    (n, m); boundary points get ``inf``, masked-out rows ``-inf``
    (reference ``non_dominate.py:206-239``).

    This sort+scatter formulation is the XLA reference implementation;
    above ``EVOX_TPU_PALLAS_CROWDING_MIN_POP`` rows with the Pallas gate
    open, the sort-free tiled neighbor kernel
    (:func:`evox_tpu.ops.crowding.crowding_distance_pallas`) dispatches
    instead — bitwise-identical results, parity-pinned in
    ``tests/test_pallas_kernels.py``."""
    n, m = costs.shape
    if _pallas_crowding_eligible(costs):
        from ...ops.crowding import crowding_distance_pallas

        return crowding_distance_pallas(costs, mask)
    if mask is None:
        mask = jnp.ones((n,), dtype=bool)
        num_valid = n
    else:
        num_valid = jnp.sum(mask)

    # Sort each objective column with invalid rows pushed to the end.
    inverted = (~mask)[:, None].astype(costs.dtype) * jnp.ones((1, m), costs.dtype)
    order = lexsort([costs, inverted], dim=0)  # (n, m) per-column row order
    sorted_costs = jnp.take_along_axis(costs, order, axis=0)
    rng = sorted_costs[num_valid - 1] - sorted_costs[0]
    distance = jnp.zeros_like(costs)
    gaps = (sorted_costs[2:] - sorted_costs[:-2]) / rng
    col = jnp.broadcast_to(jnp.arange(m)[None, :], (n - 2, m))
    distance = distance.at[order[1:-1], col].set(gaps)
    distance = distance.at[order[0], jnp.arange(m)].set(jnp.inf)
    distance = distance.at[order[num_valid - 1], jnp.arange(m)].set(jnp.inf)
    distance = jnp.where(mask[:, None], distance, -jnp.inf)
    return jnp.sum(distance, axis=1)


def nd_environmental_selection(
    x: jax.Array, f: jax.Array, topk: int
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """NSGA-II survivor selection: non-domination rank, then crowding distance
    on the boundary front (reference ``non_dominate.py:242-262``).

    :return: ``(selected_x, selected_f, rank, crowding_distance)``.
    """
    # Ranking may stop once the front crossing ``topk`` is fully peeled:
    # deeper rows can never be selected, their sentinel rank (= n) sorts
    # after every real rank, and the boundary front/worst_rank are exact
    # because peeling always completes whole fronts.
    rank = non_dominate_rank(f, until_count=topk)
    if _pallas_topk_eligible(rank):
        from ...ops.topk import masked_top_k

        # k-th smallest rank via the rank-by-count kernel: the same
        # value lax.top_k's bitonic sort returns, without the sort.
        worst_rank = masked_top_k(rank, topk)[0][-1]
    else:
        worst_rank = -jax.lax.top_k(-rank, topk)[0][-1]
    mask = rank == worst_rank
    crowding_dis = crowding_distance(f, mask)
    combined_order = lexsort([-crowding_dis, rank])[:topk]
    return (
        x[combined_order],
        f[combined_order],
        rank[combined_order],
        crowding_dis[combined_order],
    )
