"""Tournament selection (reference:
``src/evox/operators/selection/tournament_selection.py:8-54``), with explicit
PRNG keys in place of global torch RNG."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ...utils import lexsort

__all__ = ["tournament_selection", "tournament_selection_multifit"]


def tournament_selection(
    key: jax.Array, n_round: int, fitness: jax.Array, tournament_size: int = 2
) -> jax.Array:
    """Single-fitness k-tournament: for each of ``n_round`` rounds draw
    ``tournament_size`` random candidates and keep the argmin-fitness one.

    :return: ``(n_round,)`` indices of the winners.
    """
    num_candidates = fitness.shape[0]
    parents = jax.random.randint(
        key, (n_round, tournament_size), 0, num_candidates
    )
    winners = jnp.argmin(fitness[parents], axis=1)
    return jnp.take_along_axis(parents, winners[:, None], axis=1).squeeze(1)


def tournament_selection_multifit(
    key: jax.Array,
    n_round: int,
    fitnesses: Sequence[jax.Array],
    tournament_size: int = 2,
) -> jax.Array:
    """Multi-fitness k-tournament: winners decided lexicographically over the
    fitness list (last entry most significant — numpy ``lexsort`` convention,
    matching the reference)."""
    fitness_tensor = jnp.stack(fitnesses, axis=1)  # (n, k)
    num_candidates = fitness_tensor.shape[0]
    parents = jax.random.randint(
        key, (n_round, tournament_size), 0, num_candidates
    )
    cand = fitness_tensor[parents]  # (n_round, tournament_size, k)
    order = lexsort([cand[..., i] for i in range(cand.shape[-1])])
    return jnp.take_along_axis(parents, order[:, :1], axis=1).squeeze(1)
