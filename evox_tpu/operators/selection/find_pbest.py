"""p-best selection (reference:
``src/evox/operators/selection/find_pbest.py:4-19``): for each individual,
pick a random member of the top-``percent`` fraction of the population.
Used by SHADE/JaDE-style adaptive DE."""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["select_rand_pbest"]


def select_rand_pbest(
    key: jax.Array, percent: float, population: jax.Array, fitness: jax.Array
) -> jax.Array:
    """:return: ``(pop_size, dim)`` p-best vectors, one per individual."""
    pop_size = population.shape[0]
    top_p_num = max(int(pop_size * percent), 1)
    pbest_pool = jnp.argsort(fitness)[:top_p_num]
    random_indices = jax.random.randint(key, (pop_size,), 0, top_p_num)
    return population[pbest_pool[random_indices]]
