"""Pallas masked top-k selection kernel (rank-by-count, sort-free).

The second op XLA handles poorly at the NSGA-II pop=50k cliff is top-k
selection over large ``k``: ``lax.top_k`` / ``sort`` lower to a bitonic
network of O(log² n) full-array HBM passes on TPU.  This kernel selects
the ``k`` lexicographically smallest ``(value, index)`` elements with **no
sort**: a tiled O(n²) count kernel computes every element's exact rank
(``rank_i = #{j : (v_j, j) < (v_i, i)}`` — a strict total order, so ranks
are a permutation), and the selected elements scatter straight to their
output positions (``out[rank_i] = i`` for ``rank_i < k``).  The count tile
is the same (B, B) VPU compare shape the dominance kernel tiles; whether
counting beats sorting at which ``n`` is decided empirically by the
``topk_50k`` / ``topk_50k_pallas`` bench twins on the next TPU sweep —
the same record-the-verdict discipline that demoted the dominance kernel.

Masked rows are excluded by treating them as ``(+inf, index)`` — they rank
after every valid element and are only selected when fewer than ``k``
valid rows exist, exactly matching the XLA reference's stable argsort of
the masked array.  NaN values rank after everything (``+inf`` and masked
rows included) with index tie-breaks among themselves — the same NaN-last
placement ``jnp.argsort`` gives, so unquarantined non-finite fitness
cannot flip the selection between the gated and ungated paths.  Parity (bitwise, ties and masks included) is pinned by
``tests/test_pallas_kernels.py``; dispatch is gated
(:mod:`evox_tpu.ops.pallas_gate`) like every Pallas kernel here.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["lex_rank", "masked_top_k", "masked_top_k_xla"]


def _big(dtype) -> jax.Array:
    """The largest representable value of ``dtype`` — the rank-last fill
    for masked rows (``+inf`` for floats; integer inputs — NSGA-II ranks
    — use the dtype max, with the index tie-break keeping the order
    strict).  One definition for the kernel and the XLA reference, so
    their masked semantics can never diverge."""
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).max, dtype)


def _pad_fill(dtype) -> jax.Array:
    """The fill for the kernel's tile-alignment PAD columns — strictly
    rank-last under the NaN-aware total order.  For floats that is NaN
    (the order's maximum: real NaN rows must still rank BEFORE pads,
    which a ``+inf`` pad would jump ahead of), resolved against real NaN
    rows by the pad's larger index; integers reuse the dtype max."""
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.nan, dtype)
    return jnp.asarray(jnp.iinfo(dtype).max, dtype)


def _rank_kernel(xi_ref, xj_ref, out_ref, *, block: int):
    """One (i-tile, j-tile) step: add the j tile's contribution to each
    i-tile element's lexicographic rank count."""
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    iota = jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)[:, 0]
    ii = (i * block + iota)[:, None]  # (B, 1) global ids of the i tile
    jj = (j * block + iota)[None, :]  # (1, B) global ids of the j tile
    a = xi_ref[0, :][:, None]  # (B, 1)
    b = xj_ref[0, :][None, :]  # (1, B)
    # NaN-aware total order matching the reference's stable argsort: NaN
    # ranks after EVERYTHING (+inf included), all NaNs tie with each
    # other (stable → resolved by index).  Plain `<`/`==` are all-false
    # around NaN, which would hand every NaN element rank 0 and clobber
    # the true minimum's scatter slot.  On integer inputs isnan folds to
    # constant-false and this is exactly the plain comparison.
    a_nan = jnp.isnan(a)
    b_nan = jnp.isnan(b)
    eq = (b == a) | (b_nan & a_nan)
    less = (b < a) | (~b_nan & a_nan) | (eq & (jj < ii))
    out_ref[0, :] += jnp.sum(less.astype(jnp.int32), axis=1)


@functools.partial(jax.jit, static_argnames=("block_size", "interpret"))
def lex_rank(
    values: jax.Array, block_size: int = 512, interpret: bool | None = None
) -> jax.Array:
    """Exact rank of every element under the strict lexicographic
    ``(value, index)`` order — a permutation of ``arange(n)`` (stable-sort
    positions), computed by tiled counting instead of sorting."""
    (n,) = values.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bs = min(block_size, n)
    n_pad = -(-n // bs) * bs
    # Pad candidates rank-last (indices >= n): under the NaN-aware order
    # the pad fill is the order's maximum, and a tie against a real
    # rank-last value loses on the larger pad index — so pads contribute
    # no counts to any real row.
    xt = jnp.pad(values[None, :], ((0, 0), (0, n_pad - n))).at[
        :, n:
    ].set(_pad_fill(values.dtype))
    ranks = pl.pallas_call(
        functools.partial(_rank_kernel, block=bs),
        grid=(n_pad // bs, n_pad // bs),
        in_specs=[
            pl.BlockSpec((1, bs), lambda i, j: (0, i)),
            pl.BlockSpec((1, bs), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, bs), lambda i, j: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
        interpret=interpret,
    )(xt, xt)
    return ranks[0, :n]


def masked_top_k_xla(
    values: jax.Array, k: int, mask: jax.Array | None = None
) -> tuple[jax.Array, jax.Array]:
    """XLA reference: the ``k`` smallest ``(value, index)`` elements of
    ``values`` with masked rows excluded (ascending; deterministic index
    tie-break via stable sort).  Returns ``(values_k, indices_k)``."""
    (n,) = values.shape
    if mask is not None:
        values = jnp.where(mask, values, _big(values.dtype))
    order = jnp.argsort(values, stable=True)[:k]
    return values[order], order.astype(jnp.int32)


def masked_top_k(
    values: jax.Array,
    k: int,
    mask: jax.Array | None = None,
    block_size: int = 512,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Masked top-k via the rank-by-count kernel — bitwise equal to
    :func:`masked_top_k_xla` (which is also the shape/semantics contract).
    """
    (n,) = values.shape
    if not 0 < k <= n:
        raise ValueError(f"k must be in 1..{n}, got {k}")
    if mask is not None:
        values = jnp.where(mask, values, _big(values.dtype))
    ranks = lex_rank(values, block_size=block_size, interpret=interpret)
    # Ranks are a permutation, so the k selected elements scatter to
    # distinct output slots; everything ranked >= k drops.
    idx = (
        jnp.zeros((k,), jnp.int32)
        .at[jnp.where(ranks < k, ranks, k)]
        .set(jnp.arange(n, dtype=jnp.int32), mode="drop")
    )
    return values[idx], idx
