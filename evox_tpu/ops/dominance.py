"""Pallas blocked dominance-matrix kernel.  **DEMOTED — opt-in only.**

The O(n²m) dominance matrix is the hot spot of non-dominated sorting
(SURVEY §2.3 ⚠ — reference ``operators/selection/non_dominate.py:6-26``
computes it as a broadcasted (n, n, m) compare).  This kernel computes the
(n, n) boolean matrix in (B, B) VMEM tiles, never materializing an
(n, n, m) intermediate: objectives are laid out ``(m, n)`` so each tile
compare is an unrolled loop of ``(B, 1) vs (1, B)`` VPU ops.

**Verdict (recorded, not hoped):** on the measured NSGA-II bench the
kernel *loses* to plain XLA — 69 vs 90 gen/s (BASELINE.md; the bit-packed
broadcast rank path fuses better and streams less).  It is therefore OFF
every default path: the general ``EVOX_TPU_PALLAS`` gate no longer
dispatches it, and it engages only with the explicit
``EVOX_TPU_PALLAS_DOMINANCE=1`` opt-in on top of the open gate (see
``operators/selection/non_dominate.py::_pallas_kernel_eligible``).  The
``nsga2_dtlz2_pallas`` bench twin keeps measuring the opt-in path so the
next TPU sweep can re-litigate the verdict — no silent dead code.  Pallas
effort is aimed instead at the ops where XLA demonstrably loses at the
pop=50k cliff: the tiled crowding-distance kernel (``ops/crowding.py``)
and the masked top-k rank-by-count kernel (``ops/topk.py``).

Falls back to interpret mode off-TPU so tests exercise the same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["dominance_matrix"]


def _dominance_kernel(xi_ref, xj_ref, out_ref, *, n_obj: int):
    # xi_ref, xj_ref: (m, B) objective columns for the row/col tile.
    le = None
    lt = None
    for k in range(n_obj):
        a = xi_ref[k, :][:, None]  # (B, 1)
        b = xj_ref[k, :][None, :]  # (1, B)
        le_k = a <= b
        lt_k = a < b
        le = le_k if le is None else (le & le_k)
        lt = lt_k if lt is None else (lt | lt_k)
    out_ref[...] = le & lt


@functools.partial(jax.jit, static_argnames=("block_size", "interpret"))
def dominance_matrix(
    f: jax.Array, block_size: int = 512, interpret: bool | None = None
) -> jax.Array:
    """Return the (n, n) boolean matrix ``A[i, j] = f_i dominates f_j``.

    :param f: objectives, (n, m) float.
    :param block_size: tile edge; rounded down to n when larger.
    :param interpret: force pallas interpret mode (default: off-TPU only).
    """
    n, m = f.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bs = min(block_size, n)
    n_pad = -(-n // bs) * bs
    # (m, n) layout: the population axis is the 128-lane axis.  The input
    # dtype is preserved for floats (downcasting would let the gated kernel
    # rank differently from the broadcast path under x64); non-float inputs
    # compare as f32.
    if not jnp.issubdtype(f.dtype, jnp.floating):
        f = f.astype(jnp.float32)
    xt = jnp.pad(f.T, ((0, 0), (0, n_pad - n)), constant_values=jnp.inf)
    out = pl.pallas_call(
        functools.partial(_dominance_kernel, n_obj=m),
        grid=(n_pad // bs, n_pad // bs),
        in_specs=[
            pl.BlockSpec((m, bs), lambda i, j: (0, i)),
            pl.BlockSpec((m, bs), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bs, bs), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n_pad, n_pad), jnp.bool_),
        interpret=interpret,
    )(xt, xt)
    return out[:n, :n]
