"""Pallas tiled crowding-distance kernel.

NSGA-II's pop=50k cliff (6.7 gen/s, BASELINE.md) is survivor selection,
and its crowding-distance step is one of the two ops XLA handles worst at
that size: the reference formulation is ``m`` stable sorts plus two
scatters over (n, m) — a lowering dominated by XLA's TPU sort (an
O(n log² n) bitonic network of full-array HBM passes) and data-dependent
scatter addressing that Mosaic handles but never tiles well.

This kernel computes the same distances with **no sort and no scatter**:
for each individual the per-objective crowding gap is
``(next_above - next_below) / range`` where next-above/next-below are its
lexicographic ``(value, index)`` neighbors — exactly the elements that sit
beside it in the reference's stable sort, so the arithmetic (and the
result, bitwise) is identical.  Finding the neighbors is an O(n²m) tiled
reduction over (B, B) VPU compare tiles — the same shape the (demoted)
dominance kernel tiles, trading asymptotic complexity for perfectly
streaming, sort-free, scatter-free memory traffic.  Whether that trade
wins at which ``n`` on real hardware is decided **empirically**: the
``crowding_50k`` / ``crowding_50k_pallas`` bench twins exist so the next
TPU sweep records the verdict (the same discipline that demoted the
dominance kernel).

The XLA reference implementation is
:func:`evox_tpu.operators.selection.crowding_distance`; parity — bitwise,
ties and masks included — is pinned by ``tests/test_pallas_kernels.py``,
and dispatch is gated (:mod:`evox_tpu.ops.pallas_gate`) exactly like
every Pallas kernel in this library.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["crowding_neighbors", "crowding_distance_pallas"]


def _neighbor_kernel(
    xi_ref,
    xj_ref,
    vj_ref,
    below_ref,
    above_ref,
    has_below_ref,
    has_above_ref,
    *,
    n_obj: int,
    block: int,
):
    """One (i-tile, j-tile) step: fold the j tile's candidates into the i
    tile's running lexicographic-neighbor accumulators.

    ``xi_ref``/``xj_ref``: (m, B) objective columns; ``vj_ref``: (1, B)
    validity of the j tile (float 0/1 — bools stay off the lane tiles);
    ``below_ref``/``above_ref``: (m, B) running max-below / min-above per
    objective over the NON-NaN candidates, accumulated across the
    sequential j grid dimension; ``has_below_ref``/``has_above_ref``:
    (m, B) float encodings of which neighbor KINDS exist.  The explicit
    existence flags (rather than sentinel ``±inf`` values) are what keep
    real ``±inf`` objective values exact: a row whose successor genuinely
    IS ``+inf`` has a neighbor, and its gap must be the reference's
    ``(inf - below)/rng`` arithmetic — not a fabricated boundary ``inf``.

    NaN discipline (matching the reference's stable sort, where NaN rows
    sort last with index tie-breaks): a NaN candidate cannot ride the
    min/max value accumulators — one NaN would poison the whole
    reduction even when a nearer finite neighbor exists — so the value
    accumulators see only non-NaN candidates, and the flag encodings
    carry the NaN side-channel:

    * ``has_below``: max of ``2.0`` (a NaN predecessor exists — only
      possible when the row itself is NaN, and then the TRUE predecessor
      is that NaN), ``1.0`` (non-NaN predecessor), ``0.0`` (none).
    * ``has_above``: max of ``1.0`` (a non-NaN successor exists — it is
      nearer than any NaN), ``0.5`` (only NaN successors), ``0.0``
      (none).

    ``crowding_neighbors`` folds the encodings back into NaN neighbor
    values + plain 0/1 existence flags.
    """
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        below_ref[...] = jnp.full_like(below_ref, -jnp.inf)
        above_ref[...] = jnp.full_like(above_ref, jnp.inf)
        has_below_ref[...] = jnp.zeros_like(has_below_ref)
        has_above_ref[...] = jnp.zeros_like(has_above_ref)

    # Global element ids of both tiles: the index component of the
    # lexicographic (value, index) order — what makes ties deterministic
    # and bitwise-equal to the reference's stable sort.
    iota = jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)[:, 0]
    ii = (i * block + iota)[:, None]  # (B, 1)
    jj = (j * block + iota)[None, :]  # (1, B)
    valid_j = vj_ref[0, :][None, :] > 0.0  # (1, B)

    for k in range(n_obj):
        a = xi_ref[k, :][:, None]  # (B, 1) i-tile values
        b = xj_ref[k, :][None, :]  # (1, B) j-tile candidates
        a_nan = jnp.isnan(a)
        b_nan = jnp.isnan(b)
        eq = (b == a) | (b_nan & a_nan)
        prec = ((b < a) | (~b_nan & a_nan) | (eq & (jj < ii))) & valid_j
        succ = ((b > a) | (b_nan & ~a_nan) | (eq & (jj > ii))) & valid_j
        below = jnp.max(jnp.where(prec & ~b_nan, b, -jnp.inf), axis=1)
        above = jnp.min(jnp.where(succ & ~b_nan, b, jnp.inf), axis=1)
        below_ref[k, :] = jnp.maximum(below_ref[k, :], below)
        above_ref[k, :] = jnp.minimum(above_ref[k, :], above)
        has_below_ref[k, :] = jnp.maximum(
            has_below_ref[k, :],
            jnp.max(
                jnp.where(prec, jnp.where(b_nan, 2.0, 1.0), 0.0), axis=1
            ).astype(has_below_ref.dtype),
        )
        has_above_ref[k, :] = jnp.maximum(
            has_above_ref[k, :],
            jnp.max(
                jnp.where(succ, jnp.where(b_nan, 0.5, 1.0), 0.0), axis=1
            ).astype(has_above_ref.dtype),
        )


@functools.partial(jax.jit, static_argnames=("block_size", "interpret"))
def crowding_neighbors(
    costs: jax.Array,
    mask: jax.Array,
    block_size: int = 512,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Per-objective lexicographic neighbor values of every row: returns
    ``(below, above, has_below, has_above)`` of shape (n, m) — the
    masked-stable-sort predecessor/successor values plus float-0/1
    existence flags (the values alone cannot distinguish "no neighbor"
    from a genuine ``±inf`` neighbor).  NaN objective values sort last
    (index tie-breaks) exactly like the reference's stable sort, so a
    row whose sort neighbor is a NaN row gets a NaN neighbor value."""
    n, m = costs.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bs = min(block_size, n)
    n_pad = -(-n // bs) * bs
    # (m, n) layout: the population axis rides the 128-lane axis (the
    # dominance kernel's layout).  Pad columns are invalid and carry +inf.
    xt = jnp.pad(costs.T, ((0, 0), (0, n_pad - n)), constant_values=jnp.inf)
    vt = jnp.pad(
        mask.astype(costs.dtype)[None, :], ((0, 0), (0, n_pad - n))
    )
    i_tile = pl.BlockSpec((m, bs), lambda i, j: (0, i))
    below, above, has_below, has_above = pl.pallas_call(
        functools.partial(_neighbor_kernel, n_obj=m, block=bs),
        grid=(n_pad // bs, n_pad // bs),
        in_specs=[
            i_tile,
            pl.BlockSpec((m, bs), lambda i, j: (0, j)),
            pl.BlockSpec((1, bs), lambda i, j: (0, j)),
        ],
        out_specs=[i_tile, i_tile, i_tile, i_tile],
        out_shape=[
            jax.ShapeDtypeStruct((m, n_pad), costs.dtype),
            jax.ShapeDtypeStruct((m, n_pad), costs.dtype),
            jax.ShapeDtypeStruct((m, n_pad), jnp.float32),
            jax.ShapeDtypeStruct((m, n_pad), jnp.float32),
        ],
        interpret=interpret,
    )(xt, xt, vt)
    below = below[:, :n].T
    above = above[:, :n].T
    has_below = has_below[:, :n].T
    has_above = has_above[:, :n].T
    # Fold the kernel's NaN side-channel encodings back into neighbor
    # VALUES + plain 0/1 existence flags: a NaN predecessor (only
    # possible for a NaN row — NaN sorts last) is the nearest one, so it
    # wins; a NaN successor is the nearest only when no non-NaN
    # successor exists.
    nan = jnp.asarray(jnp.nan, costs.dtype)
    below = jnp.where(has_below >= 2.0, nan, below)
    above = jnp.where((has_above > 0.0) & (has_above < 1.0), nan, above)
    return (
        below,
        above,
        (has_below > 0.0).astype(jnp.float32),
        (has_above > 0.0).astype(jnp.float32),
    )


def crowding_distance_pallas(
    costs: jax.Array,
    mask: jax.Array | None = None,
    block_size: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Crowding distance via the tiled neighbor kernel — bitwise equal to
    the XLA reference :func:`~evox_tpu.operators.selection.
    crowding_distance` (boundary rows ``inf``, masked-out rows ``-inf``).
    """
    n, m = costs.shape
    if mask is None:
        mask = jnp.ones((n,), dtype=bool)
    below, above, has_below, has_above = crowding_neighbors(
        costs, mask, block_size=block_size, interpret=interpret
    )
    # Per-column valid range — the ends of the reference's sorted array.
    # NaN-last ordering makes the two ends ASYMMETRIC: the top end
    # (sorted[num_valid-1]) IS a NaN when any valid value is NaN (plain
    # max propagates it), while the bottom end (sorted[0]) is the
    # smallest non-NaN value (nanmin; all-NaN columns collapse to NaN).
    mx = jnp.max(jnp.where(mask[:, None], costs, -jnp.inf), axis=0)
    mn = jnp.nanmin(jnp.where(mask[:, None], costs, jnp.nan), axis=0)
    rng = mx - mn
    # Boundary = a MISSING neighbor (existence flags, not value
    # sentinels): a row whose neighbor genuinely is ±inf takes the
    # arithmetic path, reproducing the reference's (above-below)/rng —
    # NaNs from inf-inf/inf included, bitwise.
    boundary = (has_below <= 0.0) | (has_above <= 0.0)
    gaps = jnp.where(
        boundary, jnp.asarray(jnp.inf, costs.dtype), (above - below) / rng
    )
    gaps = jnp.where(mask[:, None], gaps, -jnp.inf)
    return jnp.sum(gaps, axis=1)
