"""Runtime gate for dispatching Pallas/Mosaic kernels.

Pallas compilation is not supported on every TPU attachment: on this
project's remote-tunnel (axon relay) attachment, a ``pallas_call`` hung the
single-client relay for >15 minutes (observed 2026-07-29; see
``.claude/skills/verify/SKILL.md``).  The library therefore never dispatches
a Pallas kernel unless the gate opens:

* ``EVOX_TPU_PALLAS`` unset / ``"0"`` — gate closed (default; XLA paths).
* ``EVOX_TPU_PALLAS=probe`` — open iff a cached capability-probe verdict for
  the CURRENT attachment identity (backend + device kind + optional
  ``EVOX_TPU_ATTACHMENT_ID``) says Pallas works.  The probe itself is
  **explicit**::

      python -m evox_tpu.ops.pallas_gate   # run the probe, cache verdict

  It runs a tiny ``pallas_call`` in a fresh subprocess with a hard timeout
  and caches the verdict (pass / fail / timeout, keyed by attachment
  identity: backend + device kind + optional ``EVOX_TPU_ATTACHMENT_ID``) at
  :data:`PROBE_RECORD_PATH`.  The probe is NOT run lazily from inside a
  trace: on single-client attachments the library's own process already
  holds the device, so a lazily-spawned probe subprocess would block on it,
  stall tracing for the full timeout, and cache a spurious "unsupported"
  verdict.  Probe once, up front, from a process that is not holding the
  attachment.
* ``EVOX_TPU_PALLAS=1`` — gate open unconditionally (you know the
  attachment supports Mosaic; no probe, no subprocess).
* Any other value — gate CLOSED, with a warning.  Fail-closed is
  deliberate: a typo must not dispatch a kernel that can hang a
  single-client relay attachment.

The reference's analogue is its custom-op registration path for the
dominance kernel (``src/evox/operators/selection/non_dominate.py:29-70``),
which torch dispatches unconditionally; the gate exists because a TPU
attachment, unlike a local CUDA device, can *hang* rather than error on an
unsupported kernel launch.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

__all__ = ["pallas_enabled", "run_capability_probe", "PROBE_RECORD_PATH"]

PROBE_RECORD_PATH = os.path.join(
    os.path.expanduser("~"), ".evox_tpu_pallas_probe.json"
)
_PROBE_TIMEOUT_S = 240

_cached: bool | None = None

_PROBE_CODE = """
import time
import jax, jax.numpy as jnp
from jax.experimental import pallas as pl

def kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0

t0 = time.time()
x = jnp.ones((8, 128), jnp.float32)
out = pl.pallas_call(
    kernel, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype)
)(x)
out.block_until_ready()
assert float(out[0, 0]) == 2.0
print(f"PALLAS_PROBE_OK elapsed={time.time() - t0:.1f}s "
      f"backend={jax.default_backend()} "
      f"kind={jax.devices()[0].device_kind}", flush=True)
"""


def _attachment_key(backend: str, device_kind: str | None) -> str:
    """Identity a verdict applies to: backend name + device kind (+ an
    optional operator-set ``EVOX_TPU_ATTACHMENT_ID``).  A bare backend name
    ("tpu") is too coarse — a verdict recorded on one Mosaic-capable
    attachment must not open the gate on a different attachment of the same
    backend type sharing this home directory (e.g. the relay type the gate
    exists to protect)."""
    parts = [backend]
    if device_kind:
        parts.append(device_kind)
    attachment_id = os.environ.get("EVOX_TPU_ATTACHMENT_ID")
    if attachment_id:
        parts.append(attachment_id)
    return "|".join(parts)


def _current_attachment_key() -> str:
    """Identity of the current process's attachment.  Calling this from
    ``pallas_enabled`` is safe: the gate is only consulted mid-trace, when a
    backend is already initialized."""
    import jax

    devices = jax.devices()
    kind = devices[0].device_kind if devices else None
    return _attachment_key(jax.default_backend(), kind)


def _load_records() -> dict:
    """The on-disk verdict store: ``{attachment_key: record}`` — one slot
    per attachment identity, so alternating CPU/TPU runs (or different TPU
    attachments sharing this home directory) don't clobber or inherit each
    other's verdict."""
    if os.path.exists(PROBE_RECORD_PATH):
        try:
            with open(PROBE_RECORD_PATH) as f:
                records = json.load(f)
            if isinstance(records, dict) and all(
                isinstance(v, dict) for v in records.values()
            ):
                return records
        except (OSError, json.JSONDecodeError):
            pass
    return {}


def run_capability_probe(timeout_s: float = _PROBE_TIMEOUT_S) -> dict:
    """Run the Pallas capability probe in a subprocess and cache the verdict
    on disk, keyed by the current attachment identity.  Returns the record
    dict ``{"ok": bool, ...}``.

    Run this from a process that is NOT already holding a single-client
    attachment (fresh shell: ``python -m evox_tpu.ops.pallas_gate``) — the
    subprocess needs to initialize the backend itself.  The parent does not
    touch JAX until the child has exited (initializing the backend here
    first would be the exact self-contention the gate exists to avoid): the
    verdict's backend key is parsed from the child's output, with a parent
    ``jax.default_backend()`` call only as the post-exit fallback.
    """
    t0 = time.time()
    record: dict = {"timeout_s": timeout_s, "probed_at": int(t0)}
    out = err = ""
    try:
        proc = subprocess.run(
            [sys.executable, "-u", "-c", _PROBE_CODE],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
        out, err = proc.stdout or "", proc.stderr or ""
        if proc.returncode == 0 and "PALLAS_PROBE_OK" in out:
            record.update(
                ok=True,
                detail=out.strip().splitlines()[-1],
                elapsed_s=round(time.time() - t0, 1),
            )
        else:
            record.update(
                ok=False,
                detail=f"rc={proc.returncode}",
                error_tail=(err or out)[-1000:],
            )
    except subprocess.TimeoutExpired:
        # NOTE: the killed child may wedge a single-client relay attachment
        # for a while (observed on axon) — which is exactly why the probe is
        # explicit and its verdict persisted.
        record.update(
            ok=False, detail=f"timeout after {timeout_s}s (Mosaic hang?)"
        )
    m = re.search(r"backend=(\w+) kind=(.+)$", out.strip(), re.MULTILINE)
    if m:
        key = _attachment_key(m.group(1), m.group(2).strip())
        record["backend"] = m.group(1)
        record["device_kind"] = m.group(2).strip()
    else:
        # Child never reported its identity (failed/timed out before init
        # completed).  The child has exited, so initializing here no longer
        # contends with it; if the attachment itself is wedged this may
        # still block — acceptable in the explicit CLI, never on a library
        # code path.
        key = _current_attachment_key()
        record["backend"] = key.split("|")[0]
    record["attachment"] = key
    records = _load_records()
    records[key] = record
    from ..utils.checkpoint import atomic_write_text

    try:
        atomic_write_text(PROBE_RECORD_PATH, json.dumps(records, indent=1))
    except OSError:
        pass
    return record


def pallas_enabled() -> bool:
    """Should Pallas kernels be dispatched in this process?  See module
    docstring for the ``EVOX_TPU_PALLAS`` contract."""
    global _cached
    if _cached is not None:
        return _cached
    flag = os.environ.get("EVOX_TPU_PALLAS", "0").strip().lower()
    if flag in ("1", "force", "on", "true"):
        _cached = True
    elif flag == "probe":
        record = _load_records().get(_current_attachment_key())
        if record is None:
            import warnings

            warnings.warn(
                "EVOX_TPU_PALLAS=probe, but no capability verdict exists "
                f"for attachment {_current_attachment_key()!r}; the gate stays CLOSED. "
                "Run `python -m evox_tpu.ops.pallas_gate` (from a fresh "
                "process, before your workload) to probe this attachment.",
                stacklevel=2,
            )
        _cached = bool(record and record.get("ok"))
    else:
        # Unset, "0", and ANY unrecognized value: gate closed (fail-closed —
        # a typo must not dispatch a kernel that can hang a single-client
        # relay attachment).
        if flag not in ("", "0", "false", "off"):
            import warnings

            warnings.warn(
                f"EVOX_TPU_PALLAS={flag!r} is not recognized; the Pallas "
                f"gate stays CLOSED (use '1', 'probe', or '0').",
                stacklevel=2,
            )
        _cached = False
    return _cached


def _reset_for_tests() -> None:
    global _cached
    _cached = None


if __name__ == "__main__":
    verdict = run_capability_probe()
    print(json.dumps(verdict, indent=1))
    sys.exit(0 if verdict.get("ok") else 1)
