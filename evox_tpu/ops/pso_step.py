"""Fused PSO move kernel (Pallas).

The measured bf16+rbg north-star PSO step lowers to two XLA mega-fusions
plus two standalone ``rng-bit-generator`` ops that XLA never fuses
(profile: ``bench_artifacts/profile_pso_northstar_bf16_rbg``) — ~2.2 GB of
HBM traffic per generation.  This kernel performs the whole PSO *move* in
ONE pass over the population: personal-best fold, in-kernel hardware PRNG
draws (the two (N, D) random tensors are never materialized in HBM),
velocity/position update and bound clamps.  Per generation it reads
pop/velocity/local-best once and writes their updates once — ~1.2 GB at
the north-star config in bf16, vs ~2.2 GB for the XLA path.

Behavioral parity: the update equations are the reference PSO's
(``src/evox/algorithms/so/pso_variants/pso.py:89-106``).  In ``rand="hw"``
mode the draws come from the TPU core PRNG (Mosaic) rather than the
key-derived Threefry stream — reproducible for a given seed on the same
topology, but not bit-identical to the XLA path (the same trade JAX's
``rbg`` PRNG makes).  ``rand="input"`` takes caller-supplied draws, which
is what the CPU/interpret-mode tests use to check exact parity against a
pure-jnp mirror of the kernel (the TPU PRNG primitives have no CPU
lowering).

Dispatch is gated like every Pallas kernel in this library
(:mod:`evox_tpu.ops.pallas_gate`): algorithms fall back to the XLA path
unless the attachment has a passing capability verdict.

Scope note: this kernel fuses *within* one generation (one HBM pass for
the move).  The other fusion axis — many generations in ONE compiled
program, so the host dispatches once per checkpoint segment instead of
once per generation — used to exist only as one-off ``fori_loop`` bench
twins; it is now the general, resilience-preserving
:meth:`StdWorkflow.run_segment <evox_tpu.workflows.StdWorkflow.run_segment>`
/ ``ResilientRunner(fused=True)`` path (quarantine, health metrics and
batched monitor telemetry ride inside the scan).  The two compose: a
``PallasPSO`` step body is fused across generations by the segment scan
exactly like the XLA step is.

Likewise the bf16+rbg configuration this kernel was profiled against is
no longer a hand-built bench recipe: it is the framework-wide numerics
plane (``evox_tpu.precision`` — ``StdWorkflow(precision=
PrecisionPolicy(), key_impl="rbg")``), which carries mapped state leaves
in bf16 storage with f32 compute at one seam and makes the partitionable
``rbg`` generator a first-class key implementation.  The XLA-path
structure this kernel hand-fuses (two mega-fusions + unfused
``rng-bit-generator`` ops) is exactly what that policy path lowers to;
the ``pso_northstar_policy`` vs ``pso_northstar_pallas`` bench twins
measure whether the in-kernel PRNG still pays on top of the policy.  See
``docs/guide/precision.md``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_pso_move", "pad_dim", "supports_shape"]


def _uniform_bits(shape, dtype):
    """Uniform [0, 1) of ``dtype`` from the in-kernel hardware PRNG."""
    # prng_random_bits returns SIGNED int32; bitcast to uint32 first so the
    # shift is logical — an arithmetic shift would keep the sign bit and
    # yield draws in [-0.5, 0.5).
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    # Use exactly as many high bits as the target mantissa holds, so every
    # k/2^m value is representable and the [0, 1) upper bound is strict —
    # converting a finer f32 draw down would round the top ulp up to 1.0.
    m = 7 if dtype == jnp.bfloat16 else 24
    u = (bits >> (32 - m)).astype(jnp.float32) * (2.0**-m)
    return u.astype(dtype)


def _pso_move_kernel(
    seed_ref,
    scal_ref,
    pop_ref,
    vel_ref,
    lbl_ref,
    fit_ref,
    lbf_ref,
    gbl_ref,
    lb_ref,
    ub_ref,
    *rest,
    rand: str,
):
    if rand == "input":
        rp_ref, rg_ref, pop_out, vel_out, lbl_out, lbf_out = rest
    else:
        pop_out, vel_out, lbl_out, lbf_out = rest
        # Distinct stream per grid block; seed once per block invocation.
        pltpu.prng_seed(seed_ref[0], pl.program_id(0), pl.program_id(1))

    pop = pop_ref[...]
    dtype = pop.dtype
    w = scal_ref[0].astype(dtype)
    phi_p = scal_ref[1].astype(dtype)
    phi_g = scal_ref[2].astype(dtype)

    # Personal-best fold (the (N, D) half of it lives here so the
    # local-best array is read and written exactly once per generation).
    fit = fit_ref[...]
    lbf = lbf_ref[...]
    # Compare in f32: Mosaic on v5e rejects bf16 vector compares
    # ("Target does not support this comparison"), and the column is
    # only (bn, 1) so the upcast is free.
    improved = fit.astype(jnp.float32) < lbf.astype(jnp.float32)  # (bn, 1)
    lbl = jnp.where(improved, pop, lbl_ref[...])
    lbf_out[...] = jnp.where(improved, fit, lbf)
    lbl_out[...] = lbl

    if rand == "input":
        rp = rp_ref[...]
        rg = rg_ref[...]
    else:
        rp = _uniform_bits(pop.shape, dtype)
        rg = _uniform_bits(pop.shape, dtype)

    vel = (
        w * vel_ref[...]
        + phi_p * rp * (lbl - pop)
        + phi_g * rg * (gbl_ref[...] - pop)
    )
    lb = lb_ref[...]
    ub = ub_ref[...]
    pop_out[...] = jnp.clip(pop + vel, lb, ub)
    vel_out[...] = jnp.clip(vel, lb, ub)


def pad_dim(d: int) -> int:
    """The feature width the kernel actually runs at: ``d`` rounded up to a
    multiple of the 128-wide lane tile.  Callers (``PallasPSO``) hold their
    state padded to this width with the pad columns pinned to zero by
    ``lb = ub = 0`` — zero-width bounds keep them at exactly 0 through every
    velocity/position update, so padding changes no real coordinate."""
    return max(128, -(-d // 128) * 128)


def _pick_col_block(d: int) -> int | None:
    """Lane-axis tile width — 128-aligned tiles ONLY.

    Lane-unaligned blocks are refused outright (``None``), not masked:
    a masked edge tile (d=1000 -> 512+488) put the remote Mosaic compile
    past 18 minutes, under which the single-client tunnel relay died
    (observed 2026-07-31; same pathology as the documented >25-min
    lane-unaligned full-width compile).  Aligned tiles — the capability
    probe's own shape class — compile in seconds.  Unaligned ``d`` must be
    padded by the caller (:func:`pad_dim`); the sub-lane full-width escape
    (``d <= 128``) is kept for interpret-mode tests, and real TPU dispatch
    via ``PallasPSO`` always pads instead of relying on it."""
    if d <= 128:
        return d
    if d % 128:
        return None
    # Largest 128-multiple tile (capped at 512 for VMEM) that DIVIDES d —
    # a non-divisor cap (e.g. 512 for d=640) would leave a masked edge
    # tile, the very pathology being refused.  128 always divides an
    # aligned d, so a full-width tiling always exists.
    for bd in (512, 384, 256):
        if d % bd == 0:
            return bd
    return 128  # always divides an aligned d


def _pick_block(n: int, d: int, itemsize: int) -> int | None:
    """Largest divisor of ``n`` that keeps ~10 live (bn, bd) blocks inside a
    conservative VMEM budget.  A divisor (not padding) because padding the
    (N, D) operands would cost an extra full read+write of the state —
    exactly the traffic the kernel exists to avoid.  Mosaic requires the
    block's sublane dim to be a multiple of 8 (or the whole array), so a
    candidate must satisfy that too; returns ``None`` when no such block
    exists (caller falls back to the XLA path)."""
    bd = _pick_col_block(d)
    if bd is None:
        return None
    budget_rows = max(8, (12 * 1024 * 1024) // (10 * bd * itemsize))
    limit = min(n, 512, budget_rows)
    bn = None
    for cand in range(8, limit + 1, 8):
        if n % cand == 0:
            bn = cand
    if bn is None and n <= limit:
        bn = n  # whole-array block is exempt from the multiple-of-8 rule
    return bn


def supports_shape(n: int, d: int, itemsize: int) -> bool:
    """Static dispatch check: True iff the kernel can serve an (n, d)
    population of the given element size — i.e. a Mosaic-legal block exists
    at the lane-padded width :func:`pad_dim` that ``PallasPSO`` actually
    dispatches."""
    return _pick_block(n, pad_dim(d), itemsize) is not None


@functools.partial(
    jax.jit, static_argnames=("rand", "block_rows", "interpret")
)
def fused_pso_move(
    pop: jax.Array,
    velocity: jax.Array,
    local_best_location: jax.Array,
    fit: jax.Array,
    local_best_fit: jax.Array,
    global_best_location: jax.Array,
    lb: jax.Array,
    ub: jax.Array,
    w: jax.Array,
    phi_p: jax.Array,
    phi_g: jax.Array,
    seed: jax.Array,
    rand_draws: tuple[jax.Array, jax.Array] | None = None,
    rand: str = "hw",
    block_rows: int | None = None,
    interpret: bool | None = None,
):
    """One fused PSO move: personal-best fold + random draws + velocity /
    position update + bound clamps, single HBM pass.

    :param pop: (N, D) positions.  ``velocity`` / ``local_best_location``
        same shape and dtype.
    :param fit: (N,) fitness of ``pop``; ``local_best_fit`` same shape.
    :param global_best_location: (D,) — fold the global best *before*
        calling (it reads only the (N,) fitness plus one row of ``pop``).
    :param w, phi_p, phi_g: scalar hyperparameters (traced values fine).
    :param seed: (1,) int32 PRNG seed for ``rand="hw"``; a per-step value
        derived from the algorithm key keeps steps decorrelated.
    :param rand_draws: ``rand="input"`` only — (rp, rg) uniforms of
        ``pop``'s shape, used instead of the in-kernel PRNG.
    :returns: ``(pop', velocity', local_best_location', local_best_fit')``.
    """
    n, d = pop.shape
    dtype = pop.dtype
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if rand not in ("hw", "input"):
        raise ValueError(f"rand must be 'hw' or 'input', got {rand!r}")
    if rand == "input" and rand_draws is None:
        raise ValueError("rand='input' requires rand_draws=(rp, rg)")
    if d % 128:
        # Lane-unaligned widths are the remote-Mosaic compile pathology
        # (masked edge tiles included) — never dispatch them on hardware.
        # Sub-lane widths (d < 128) are tolerated in interpret mode only,
        # where no Mosaic compile happens, so tests can run natural shapes.
        if d > 128 or not interpret:
            raise ValueError(
                f"fused_pso_move: feature dim {d} is not lane-aligned — an "
                f"unaligned tile hangs the remote Mosaic compile.  Pad the "
                f"feature axis to pad_dim({d})={pad_dim(d)} with lb=ub=0 "
                f"pad columns (PallasPSO does this automatically)."
            )

    bn = block_rows or _pick_block(n, d, dtype.itemsize)
    if bn is None:
        raise ValueError(
            f"fused_pso_move: no Mosaic-legal block for pop shape ({n}, {d}) "
            f"— pop_size needs a divisor that is a multiple of 8 within the "
            f"VMEM budget.  Note supports_shape() answers for the "
            f"lane-padded width pad_dim(d) that PallasPSO dispatches, not "
            f"for raw unpadded operands."
        )
    if n % bn:
        raise ValueError(
            f"fused_pso_move: block_rows={bn} does not divide pop_size={n}; "
            f"the tail rows would be left unwritten."
        )
    bd = _pick_col_block(d)
    # 2-D grid: rows x lane-tiles.  The per-row fold quantities ((bn, 1)
    # blocks) are re-read and re-written per lane tile — idempotent and a
    # rounding error next to the (bn, bd) traffic.
    grid = (n // bn, -(-d // bd))

    scal = jnp.stack(
        [
            jnp.asarray(w, jnp.float32),
            jnp.asarray(phi_p, jnp.float32),
            jnp.asarray(phi_g, jnp.float32),
        ]
    )
    fit2 = fit.astype(dtype).reshape(n, 1)
    lbf2 = local_best_fit.astype(dtype).reshape(n, 1)
    gbl2 = global_best_location.astype(dtype).reshape(1, d)
    lb2 = jnp.broadcast_to(lb.astype(dtype), (d,)).reshape(1, d)
    ub2 = jnp.broadcast_to(ub.astype(dtype), (d,)).reshape(1, d)

    nd_spec = pl.BlockSpec((bn, bd), lambda i, j: (i, j))
    n1_spec = pl.BlockSpec((bn, 1), lambda i, j: (i, 0))
    row_spec = pl.BlockSpec((1, bd), lambda i, j: (0, j))
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # seed
        pl.BlockSpec(memory_space=pltpu.SMEM),  # scalars
        nd_spec,  # pop
        nd_spec,  # velocity
        nd_spec,  # local_best_location
        n1_spec,  # fit
        n1_spec,  # local_best_fit
        row_spec,  # global_best_location
        row_spec,  # lb
        row_spec,  # ub
    ]
    operands = [
        jnp.asarray(seed, jnp.int32).reshape(1),
        scal,
        pop,
        velocity,
        local_best_location,
        fit2,
        lbf2,
        gbl2,
        lb2,
        ub2,
    ]
    if rand == "input":
        rp, rg = rand_draws
        in_specs += [nd_spec, nd_spec]
        operands += [rp.astype(dtype), rg.astype(dtype)]

    new_pop, new_vel, new_lbl, new_lbf = pl.pallas_call(
        functools.partial(_pso_move_kernel, rand=rand),
        grid=grid,
        in_specs=in_specs,
        out_specs=[nd_spec, nd_spec, nd_spec, n1_spec],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), dtype),
            jax.ShapeDtypeStruct((n, d), dtype),
            jax.ShapeDtypeStruct((n, d), dtype),
            jax.ShapeDtypeStruct((n, 1), dtype),
        ],
        interpret=interpret,
    )(*operands)
    return new_pop, new_vel, new_lbl, new_lbf.reshape(n)
