"""Gateway client: retrying, idempotent, stdlib-only HTTP front-door SDK.

The other half of :mod:`evox_tpu.service.gateway`: a client whose retry
loop is **safe by construction** — every mutating call mints one
idempotency key per *logical operation* and reuses it across every
retry, so dropped requests, dropped replies, torn replies, and full
daemon SIGKILL+restart cycles all collapse to exactly-once admission on
the server (the key rides the journal).  The loop backs off
capped-exponentially on transport errors and honors ``Retry-After`` on
429/503, which means a fleet of these clients load-sheds itself by
exactly the daemon's live measured segment cadence.

Module import is stdlib-only (``http.client``, ``json``, ``uuid``) — a
bench or operator process pays no jax import to *talk* to a daemon;
only :func:`encode_spec` (pickling an actual :class:`TenantSpec`)
touches the heavy stack, lazily.

The transport seam is one method — ``request(method, path, headers,
body) -> (status, headers, body_bytes)`` — so
:class:`~evox_tpu.resilience.FaultyTransport` can wrap
:class:`HttpTransport` and inject wire chaos between the retry loop and
the socket.  A reply whose JSON body fails to parse (torn reply) is
retried exactly like a dropped one: the ack the client finally returns
is always a whole, parsed, durable fact.
"""

from __future__ import annotations

import http.client
import json
import time
import uuid
from typing import Any, Callable
from urllib.parse import quote, urlparse

__all__ = ["GatewayClient", "GatewayError", "HttpTransport", "encode_spec"]

# Statuses that mean "try the same request again later"; everything else
# 4xx/5xx is a truthful terminal answer.
_RETRYABLE_STATUSES = frozenset({429, 503})


def encode_spec(spec: Any) -> dict[str, str]:
    """The wire form of an exact :class:`TenantSpec` —
    ``{"format": "pickle", "blob": <base64>}``, byte-identical to the
    daemon journal's own spec encoding (imported from it, not
    reimplemented), which is what makes an HTTP-submitted run
    bit-identical to a Python-submitted one."""
    from .daemon import _encode_spec

    return {"format": "pickle", "blob": _encode_spec(spec)}


class GatewayError(RuntimeError):
    """A terminal (non-retryable, or retries-exhausted) API error.

    :ivar status: HTTP status code (0 when the wire itself gave out).
    :ivar error: the structured machine-readable code from the reply.
    :ivar retry_after: server back-off hint in seconds, when one came.
    """

    def __init__(
        self,
        status: int,
        error: str,
        detail: str,
        *,
        retry_after: float | None = None,
    ):
        super().__init__(f"[{status}] {error}: {detail}")
        self.status = int(status)
        self.error = str(error)
        self.detail = str(detail)
        self.retry_after = retry_after


class HttpTransport:
    """One-connection-per-request stdlib transport (deliberately simple:
    no pooling means no cross-request state for chaos to corrupt)."""

    def __init__(self, host: str, port: int, *, timeout: float = 35.0):
        self.host = str(host)
        self.port = int(port)
        self.timeout = float(timeout)

    def request(
        self,
        method: str,
        path: str,
        headers: dict[str, str],
        body: bytes,
    ) -> tuple[int, dict[str, str], bytes]:
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=self.timeout
        )
        try:
            conn.request(method, path, body=body or None, headers=headers)
            response = conn.getresponse()
            payload = response.read()
            return (
                int(response.status),
                {k: v for k, v in response.getheaders()},
                payload,
            )
        finally:
            conn.close()


class GatewayClient:
    """Front-door SDK for one principal.

    :param base_url: the endpoint base (``daemon.endpoint.url`` /
        ``gateway.url`` with or without the ``/api/v1`` suffix).
    :param token: the principal's bearer token.
    :param transport: the wire seam; defaults to :class:`HttpTransport`
        at ``base_url``'s host:port.  Tests wrap it in
        :class:`~evox_tpu.resilience.FaultyTransport`.
    :param max_retries: retries *beyond* the first attempt for transport
        errors / torn replies / 429 / 503.  ``0`` = fail fast (the
        chaos tests use this to observe a lost ack, then retry by hand
        with the same key).
    :param backoff: initial retry sleep; doubles per retry up to
        ``backoff_cap`` (capped exponential — no jitter, so chaos
        schedules stay deterministic).
    :param retry_after_cap: ceiling on honoring a server ``Retry-After``
        (tests shrink it so a 1 s hint doesn't dominate the clock).
    :param sleep: injectable sleeper (tests pass a recorder).
    """

    def __init__(
        self,
        base_url: str,
        token: str,
        *,
        transport: Any | None = None,
        max_retries: int = 5,
        backoff: float = 0.05,
        backoff_cap: float = 2.0,
        retry_after_cap: float = 60.0,
        timeout: float = 35.0,
        sleep: Callable[[float], None] = time.sleep,
    ):
        parsed = urlparse(base_url if "//" in base_url else f"//{base_url}")
        if not parsed.hostname or not parsed.port:
            raise ValueError(
                f"base_url must carry host:port, got {base_url!r}"
            )
        self.prefix = "/api/v1"
        self.token = str(token)
        self.transport = transport or HttpTransport(
            parsed.hostname, parsed.port, timeout=timeout
        )
        self.max_retries = int(max_retries)
        self.backoff = float(backoff)
        self.backoff_cap = float(backoff_cap)
        self.retry_after_cap = float(retry_after_cap)
        self.sleep = sleep
        self.retries = 0  # total retry sleeps taken (test observability)

    # -- API methods ---------------------------------------------------------
    def submit(
        self,
        spec: Any = None,
        *,
        catalog: dict[str, Any] | None = None,
        tenant_class: str = "standard",
        idem_key: str | None = None,
    ) -> dict[str, Any]:
        """Submit one tenant; returns the ack dict (``tenant_id``,
        ``uid``, ``status``).  Pass either a :class:`TenantSpec` (exact,
        bit-reproducible) or ``catalog=`` (the JSON form).  The
        idempotency key defaults to a fresh UUID reused across this
        call's retries; pass ``idem_key=`` to span retries across
        *client* restarts too."""
        if (spec is None) == (catalog is None):
            raise ValueError("pass exactly one of spec or catalog")
        body: dict[str, Any] = dict(catalog or {})
        if spec is not None:
            body["spec"] = encode_spec(spec)
        body["tenant_class"] = tenant_class
        return self._request(
            "POST",
            "/tenants",
            body=body,
            idem_key=idem_key or self.new_idem_key(),
        )

    def steer(
        self,
        tenant_id: str,
        *,
        n_steps: int | None = None,
        checkpoint_every: int | None = None,
        max_restarts: int | None = None,
        idem_key: str | None = None,
    ) -> dict[str, Any]:
        """Durably adjust a live tenant's budget/cadence/restart knobs
        (applies at the next segment boundary)."""
        body = {
            k: v
            for k, v in (
                ("n_steps", n_steps),
                ("checkpoint_every", checkpoint_every),
                ("max_restarts", max_restarts),
            )
            if v is not None
        }
        return self._request(
            "POST",
            f"/tenants/{quote(tenant_id, safe='')}/steer",
            body=body,
            idem_key=idem_key or self.new_idem_key(),
        )

    def withdraw(
        self, tenant_id: str, *, idem_key: str | None = None
    ) -> dict[str, Any]:
        return self._request(
            "DELETE",
            f"/tenants/{quote(tenant_id, safe='')}",
            idem_key=idem_key or self.new_idem_key(),
        )

    def status(self, tenant_id: str) -> dict[str, Any]:
        return self._request("GET", f"/tenants/{quote(tenant_id, safe='')}")

    def result(self, tenant_id: str, *, wait: float = 0.0) -> dict[str, Any]:
        """The tenant's result document; ``wait`` long-polls server-side.
        Raises :class:`GatewayError` with ``status=202`` semantics
        avoided — a still-running tenant returns its snapshot with
        ``status != "completed"``; check the field."""
        return self._request(
            "GET",
            f"/tenants/{quote(tenant_id, safe='')}/result?wait={float(wait)}",
            accept_statuses=(200, 202),
        )

    def result_npz(self, tenant_id: str) -> tuple[str, bytes]:
        """The newest checkpoint archive, raw: ``(name, bytes)`` — for
        client-side bit-identity verification."""
        status, headers, payload = self._raw(
            "GET",
            f"/tenants/{quote(tenant_id, safe='')}/result?format=npz",
        )
        if status != 200:
            raise self._error_from(status, headers, payload)
        name = ""
        for key, value in headers.items():
            if key.lower() == "x-checkpoint-name":
                name = value
        return name, payload

    def flight(
        self, tenant_id: str, *, after: int = -1, wait: float = 0.0
    ) -> list[dict[str, Any]]:
        reply = self._request(
            "GET",
            f"/tenants/{quote(tenant_id, safe='')}/flight"
            f"?after={int(after)}&wait={float(wait)}",
        )
        return list(reply.get("rows", []))

    @staticmethod
    def new_idem_key() -> str:
        return uuid.uuid4().hex

    # -- retry loop ----------------------------------------------------------
    def _request(
        self,
        method: str,
        path: str,
        *,
        body: dict[str, Any] | None = None,
        idem_key: str | None = None,
        accept_statuses: tuple[int, ...] = (200, 201),
    ) -> dict[str, Any]:
        payload = (
            json.dumps(body).encode("utf-8") if body is not None else b""
        )
        attempt = 0
        delay = self.backoff
        while True:
            retry_hint: float | None = None
            try:
                status, headers, reply = self._raw(
                    method,
                    path,
                    body=payload,
                    # Not a PRNG key: the idempotency token MUST repeat
                    # verbatim on every retry — reuse is the contract.
                    idem_key=idem_key,  # graftlint: disable=GL001
                )
                if status in accept_statuses:
                    return self._parse(reply)
                error = self._error_from(status, headers, reply)
                if status not in _RETRYABLE_STATUSES:
                    raise error
                retry_hint = error.retry_after
                failure: Exception = error
            except OSError as e:
                # Covers real socket errors, injected TransportError, and
                # _TornReply (all ConnectionError subclasses): the request
                # or its reply was lost or mangled — the idempotency key
                # is what makes the retry safe.
                failure = e
            if attempt >= self.max_retries:
                raise failure
            attempt += 1
            self.retries += 1
            pause = delay
            if retry_hint is not None:
                pause = max(pause, min(retry_hint, self.retry_after_cap))
            self.sleep(pause)
            delay = min(delay * 2.0, self.backoff_cap)

    def _raw(
        self,
        method: str,
        path: str,
        *,
        body: bytes = b"",
        idem_key: str | None = None,
    ) -> tuple[int, dict[str, str], bytes]:
        headers = {
            "Authorization": f"Bearer {self.token}",
            "Content-Type": "application/json",
        }
        if idem_key is not None:
            headers["Idempotency-Key"] = idem_key
        return self.transport.request(
            method, self.prefix + path, headers, body
        )

    @staticmethod
    def _parse(reply: bytes) -> dict[str, Any]:
        try:
            parsed = json.loads(reply.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise _TornReply(
                f"unparseable reply body ({e}); treating as lost"
            ) from e
        if not isinstance(parsed, dict):
            raise _TornReply(f"reply is not an object: {parsed!r}")
        return parsed

    def _error_from(
        self, status: int, headers: dict[str, str], reply: bytes
    ) -> GatewayError:
        error, detail = "http-error", reply.decode("utf-8", "replace")
        try:
            doc = json.loads(reply.decode("utf-8"))
            if isinstance(doc, dict):
                error = str(doc.get("error", error))
                detail = str(doc.get("detail", detail))
        except (ValueError, UnicodeDecodeError):
            pass
        retry_after: float | None = None
        for key, value in headers.items():
            if key.lower() == "retry-after":
                try:
                    retry_after = float(value)
                except ValueError:
                    pass
        return GatewayError(status, error, detail, retry_after=retry_after)


class _TornReply(ConnectionError):
    """A reply arrived but its body is not whole JSON — retryable, and
    only safe to retry because of idempotency keys."""
