"""Network front door: authenticated, crash-safe write API on the wire.

Thirteen PRs in, tenants still entered the serving stack only as Python
calls on the daemon's own process; the PR-13 HTTP plane is read-only
introspection.  :class:`Gateway` adds the write half — submit, steer,
withdraw, fetch — on the **same** endpoint plane (one port, one server
thread pool), built to the same survive-anything standard as the journal
underneath it:

* **Ack-after-append, on the wire.**  Every mutating reply is sent only
  after the daemon's journal append fsync'd (the PR-11 crash-safety
  contract extended to HTTP): a client that holds a 2xx holds a durable
  fact.  A daemon killed before the append never admitted anything; one
  killed after the append but before the reply *did* — which is exactly
  why the next bullet exists.
* **Exactly-once admission via idempotency keys.**  Mutating requests
  carry an ``Idempotency-Key`` header (required on submit, honored on
  steer/withdraw); the key rides the journal record itself
  (``journal_extra``), so :meth:`Gateway.start` rebuilds the dedup map
  from replay and a client retrying one key across a daemon
  SIGKILL+restart gets the original ack back (``200``, with
  ``"idempotent_replay": true``) instead of a second admission.  Keys
  are namespaced per principal — two tenants cannot collide each other's
  retries.
* **Auth namespaces the filesystem.**  ``Authorization: Bearer <token>``
  maps to a *principal*; every externally-supplied tenant id is
  validated as a safe path component (:func:`validate_tenant_id` — the
  hostile-id 400), then qualified as ``<principal>--<tenant_id>`` before
  it touches the daemon, so checkpoint namespaces
  (``<root>/tenants/<principal>--<id>/``) and flight bundles are
  per-principal by construction and one principal can neither see nor
  collide another's tenants (cross-principal reads are 404, not 403 —
  existence is not leaked).
* **Overload speaks HTTP.**  ``AdmissionError(reason="shed")`` maps to
  429 and ``"queue-full"``/``"journal-failed"`` to 503, both with a
  ``Retry-After`` header computed from the **live measured** segment
  cadence (:func:`~evox_tpu.service.retry_after_seconds` — the same
  helper that fills ``stats.rejections``), so a dumb HTTP client backs
  off by exactly the hint the Python API gets.

Wire surface (all under ``/api/v1``, all JSON unless noted)::

    POST   /api/v1/tenants                submit (201; idem replay 200)
    DELETE /api/v1/tenants/<id>           withdraw/park (evict record)
    POST   /api/v1/tenants/<id>/steer     journaled steer record
    GET    /api/v1/tenants/<id>           status snapshot
    GET    /api/v1/tenants/<id>/result    ?wait=S long-poll; ?format=npz
                                          streams the newest checkpoint
    GET    /api/v1/tenants/<id>/flight    ?after=G&wait=S flight-ring rows

Submit bodies name the spec either as the exact Python object
(``{"spec": {"format": "pickle", "blob": "<base64>"}}`` — what
:class:`~evox_tpu.service.client.GatewayClient` sends; byte-identical to
the journal's own spec encoding, which is what makes HTTP-submitted runs
bit-identical to Python-submitted ones) or as a small JSON catalog form
(``{"algorithm": {"kind": "PSO", ...}, "problem": {"kind": "Ackley"},
...}``) for curl-level clients.  Pickle deserialization is gated behind
authentication by design — a bearer token is operator-level trust here.

Threading: endpoint handler threads call :meth:`handle` concurrently
with the serving loop.  One :class:`threading.RLock` (``gateway.lock``)
serializes every **mutating** route with the daemon's boundary rounds —
:meth:`pump`/:meth:`serve` take it per round, so a submit never lands
mid-boundary.  Read routes (status/result/flight) take it only for the
snapshot instant, never across a long-poll sleep.

Chaos story: :class:`~evox_tpu.resilience.FaultyTransport` injects
dropped/duplicated/torn/delayed requests and replies on the client seam,
and ``tests/test_gateway.py`` drives the kill-at-every-boundary matrix
entirely through HTTP — the acceptance bar is bit-identical final state,
monitor history, and checkpoint leaf digests versus the same specs
submitted via the Python API.
"""

from __future__ import annotations

import json
import math
import threading
import time
from dataclasses import replace as dataclass_replace
from typing import Any, Callable
from urllib.parse import parse_qs, unquote, urlparse

import numpy as np

from ..obs.endpoint import IntrospectionEndpoint
from ..obs.slo import SIGNAL_GATEWAY
from .service import AdmissionError, retry_after_seconds
from .tenant import TenantStatus, validate_tenant_id

__all__ = ["Gateway", "PRINCIPAL_SEP"]

#: Separator between the authenticated principal and the caller's tenant
#: id in the qualified (daemon-side) id.  Both halves are validated
#: ``[A-Za-z0-9._-]+`` and the principal may not contain the separator,
#: so the split is unambiguous and the joined id stays a safe path
#: component.
PRINCIPAL_SEP = "--"

# Long-poll waits are capped: a handler thread parked forever on a
# never-completing tenant would pin server threads without bound.
MAX_WAIT_SECONDS = 30.0
_POLL_SECONDS = 0.05

_JSON = "application/json"


class _ApiError(Exception):
    """One structured HTTP error reply: ``(status, error, detail)``."""

    def __init__(
        self,
        status: int,
        error: str,
        detail: str,
        *,
        retry_after: float | None = None,
    ):
        super().__init__(detail)
        self.status = int(status)
        self.error = str(error)
        self.detail = str(detail)
        self.retry_after = retry_after


class Gateway:
    """The write API, attached to a daemon's introspection endpoint.

    :param daemon: the :class:`~evox_tpu.service.ServiceDaemon` to front.
        When it already has an endpoint the gateway rides it (one port
        serves both planes); otherwise a loopback OS-assigned-port
        endpoint is created and wired to the daemon's own providers.
    :param tokens: ``{bearer_token: principal}`` — the auth table.
        Principals are validated as safe path components and may not
        contain ``"--"`` (the qualification separator).  Two tokens may
        map to one principal (key rotation).
    :param host: bind address when the gateway must create the endpoint.
    :param port: TCP port ditto (``0`` = OS-assigned).

    Call :meth:`start` before serving: it starts the daemon (journal
    replay), rebuilds the idempotency dedup map from the replayed
    records, and starts the HTTP server.  Then either drive boundaries
    yourself under ``gateway.lock`` or call :meth:`pump`/:meth:`serve`.
    """

    def __init__(
        self,
        daemon: Any,
        *,
        tokens: dict[str, str],
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        if not tokens:
            raise ValueError(
                "tokens must name at least one bearer token -> principal "
                "(an unauthenticated write API is not a configuration)"
            )
        for token, principal in tokens.items():
            if not token or not isinstance(token, str):
                raise ValueError("bearer tokens must be non-empty strings")
            validate_tenant_id(principal)
            if PRINCIPAL_SEP in principal:
                raise ValueError(
                    f"principal {principal!r} contains {PRINCIPAL_SEP!r} "
                    f"(the principal/tenant separator must stay unambiguous)"
                )
        self.daemon = daemon
        self.tokens = dict(tokens)
        #: Serializes mutating routes with serving-loop boundaries; hold
        #: it around any daemon.step() you drive yourself.
        self.lock = threading.RLock()
        self._idem: dict[str, dict[str, Any]] = {}
        self._requests: dict[tuple[str, int], int] = {}
        self._auth_rejects = 0
        self._idem_replays = 0
        self._retry_after_sent = 0
        self._started = False
        # An attached ChaosConductor registers itself here; the gateway
        # statusz section then carries the live run's chaos strip too.
        self.chaos: Any | None = None
        if daemon.endpoint is None:
            daemon.endpoint = IntrospectionEndpoint(
                metrics=daemon._metrics_text,
                healthz=daemon._healthz,
                statusz=daemon._statusz,
                flight=daemon._flight_window,
                instrument=daemon._registry,
                api=self.handle,
                host=host,
                port=port,
            )
        else:
            daemon.endpoint.api = self.handle
        daemon.gateway = self

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Gateway":
        """Start daemon (journal replay) + endpoint, rebuild the
        idempotency map from the replayed journal (idempotent)."""
        if self._started:
            return self
        with self.lock:
            self.daemon.start()
            self._rebuild_idem()
            if not self.daemon.endpoint.started:
                self.daemon.endpoint.start()
            self._started = True
        return self

    def _rebuild_idem(self) -> None:
        """Exactly-once across restarts: every journaled mutating record
        carries its idempotency key (``journal_extra``), so a second,
        read-only replay rebuilds the dedup map the in-memory half lost
        with the killed process.  Later records win (a resubmit after a
        retire is a fresh admission under a fresh key).

        Compaction-safe: a snapshot-anchored journal folds pre-anchor
        dedup entries into the snapshot's ``idem`` map (the daemon fold
        mirrors this exact entry shape), so a client retry straddling a
        compaction still replays its ack instead of double-admitting."""
        try:
            records, _damage = self.daemon.journal.replay()
        except Exception:  # pragma: no cover - replay already warned
            return
        snapshot = self.daemon.journal.snapshot_state or {}
        for token, entry in (snapshot.get("idem") or {}).items():
            self._idem[str(token)] = dict(entry)
        for rec in records:
            key = rec.data.get("idem")
            principal = rec.data.get("principal")
            if not key or not principal:
                continue
            self._idem[f"{principal}:{key}"] = {
                "route": rec.kind,
                "tenant_id": rec.data.get("tenant_id"),
                "uid": rec.data.get("uid"),
                "knobs": {
                    k: rec.data[k]
                    for k in ("n_steps", "checkpoint_every", "max_restarts")
                    if rec.kind == "steer" and k in rec.data
                },
            }

    @property
    def url(self) -> str:
        return f"{self.daemon.endpoint.url}/api/v1"

    def close(self) -> None:
        self.daemon.close()

    # -- serving loop --------------------------------------------------------
    def pump(self, max_rounds: int | None = None) -> int:
        """Drive daemon boundaries under the gateway lock; returns the
        number of rounds executed (stops early when the daemon goes
        idle).  The lock is released between rounds, so mutating HTTP
        requests interleave at exactly boundary granularity."""
        self.start()
        rounds = 0
        while max_rounds is None or rounds < max_rounds:
            with self.lock:
                busy = self.daemon.step()
            rounds += 1
            if not busy:
                break
        return rounds

    def serve(
        self,
        *,
        stop: Callable[[], bool] | None = None,
        idle_sleep: float = 0.05,
    ) -> None:
        """Run boundaries until ``stop()`` goes truthy, sleeping
        ``idle_sleep`` whenever the daemon reports idle (submissions
        arriving over HTTP wake it on the next round)."""
        self.start()
        while stop is None or not stop():
            with self.lock:
                busy = self.daemon.step()
            if not busy:
                if stop is None:
                    break
                time.sleep(idle_sleep)

    # -- the one entry point (endpoint api= seam) ----------------------------
    def handle(
        self,
        method: str,
        raw_path: str,
        headers: dict[str, str],
        body: bytes,
    ) -> tuple[int, str, "str | bytes", "dict[str, str] | None"]:
        """Serve one ``/api/...`` request; never raises (the endpoint
        would 500 — here even a handler bug becomes structured JSON)."""
        route = "other"
        try:
            parsed = urlparse(raw_path)
            query = {
                k: v[-1] for k, v in parse_qs(parsed.query).items() if v
            }
            principal = self._authenticate(headers)
            route, reply = self._route(
                method, parsed.path, query, headers, body, principal
            )
            self._observe(route, reply[0])
            return reply
        except _ApiError as e:
            self._observe(route, e.status)
            extra: dict[str, str] | None = None
            if e.retry_after is not None:
                extra = {"Retry-After": str(max(1, math.ceil(e.retry_after)))}
                self._retry_after_sent += 1
            body_out = json.dumps(
                {
                    "error": e.error,
                    "detail": e.detail,
                    **(
                        {"retry_after_seconds": float(e.retry_after)}
                        if e.retry_after is not None
                        else {}
                    ),
                }
            )
            return e.status, _JSON, body_out, extra
        except Exception as e:  # noqa: BLE001 - fail-safe by contract
            self._observe(route, 500)
            return (
                500,
                _JSON,
                json.dumps(
                    {"error": "internal", "detail": f"{type(e).__name__}: {e}"}
                ),
                None,
            )

    # -- auth ----------------------------------------------------------------
    def _authenticate(self, headers: dict[str, str]) -> str:
        auth = ""
        for name, value in headers.items():
            if name.lower() == "authorization":
                auth = value.strip()
                break
        if not auth.startswith("Bearer "):
            self._auth_rejects += 1
            self._inc("evox_gateway_auth_rejects_total")
            raise _ApiError(
                401,
                "unauthenticated",
                "missing 'Authorization: Bearer <token>' header",
            )
        principal = self.tokens.get(auth[len("Bearer ") :].strip())
        if principal is None:
            self._auth_rejects += 1
            self._inc("evox_gateway_auth_rejects_total")
            raise _ApiError(401, "unauthenticated", "unknown bearer token")
        return principal

    def _qualify(self, principal: str, tenant_id: Any) -> str:
        try:
            validate_tenant_id(tenant_id)
        except ValueError as e:
            raise _ApiError(400, "bad-tenant-id", str(e)) from e
        return f"{principal}{PRINCIPAL_SEP}{tenant_id}"

    def _resolve(self, principal: str, tenant_id: str) -> Any:
        """A principal's tenant record; 404 for anything else —
        including other principals' live ids (no existence leak)."""
        qualified = self._qualify(principal, tenant_id)
        record = self.daemon.service._tenants.get(qualified)
        if record is None:
            raise _ApiError(
                404, "unknown-tenant", f"no tenant {tenant_id!r}"
            )
        return record

    # -- routing -------------------------------------------------------------
    def _route(
        self,
        method: str,
        path: str,
        query: dict[str, str],
        headers: dict[str, str],
        body: bytes,
        principal: str,
    ) -> tuple[str, tuple[int, str, "str | bytes", "dict[str, str] | None"]]:
        prefix = "/api/v1/tenants"
        if path == prefix or path == prefix + "/":
            if method != "POST":
                raise _ApiError(405, "method", f"{method} not allowed here")
            return "submit", self._submit(principal, headers, body)
        if not path.startswith(prefix + "/"):
            raise _ApiError(404, "not-found", f"no route {path!r}")
        rest = [unquote(p) for p in path[len(prefix) + 1 :].split("/") if p]
        if not rest:
            raise _ApiError(404, "not-found", f"no route {path!r}")
        tenant_id, action = rest[0], (rest[1] if len(rest) > 1 else None)
        if len(rest) > 2:
            raise _ApiError(404, "not-found", f"no route {path!r}")
        if action is None and method == "DELETE":
            return "withdraw", self._withdraw(principal, tenant_id, headers)
        if action is None and method == "GET":
            return "status", self._status(principal, tenant_id)
        if action == "steer" and method == "POST":
            return "steer", self._steer(principal, tenant_id, headers, body)
        if action == "result" and method == "GET":
            return "result", self._result(principal, tenant_id, query)
        if action == "flight" and method == "GET":
            return "flight", self._flight(principal, tenant_id, query)
        raise _ApiError(
            405 if action in (None, "steer", "result", "flight") else 404,
            "method" if action in (None, "steer", "result", "flight") else "not-found",
            f"{method} {path!r} is not part of the API",
        )

    # -- idempotency ---------------------------------------------------------
    def _idem_key(
        self, principal: str, headers: dict[str, str], *, required: bool
    ) -> str | None:
        for name, value in headers.items():
            if name.lower() == "idempotency-key" and value.strip():
                return f"{principal}:{value.strip()}"
        if required:
            raise _ApiError(
                400,
                "missing-idempotency-key",
                "submit requires an 'Idempotency-Key' header: it is what "
                "makes your retries exactly-once across daemon restarts",
            )
        return None

    def _idem_replay(
        self, key: str | None
    ) -> tuple[int, str, str, None] | None:
        if key is None:
            return None
        ack = self._idem.get(key)
        if ack is None:
            return None
        self._idem_replays += 1
        self._inc("evox_gateway_idem_replays_total")
        qualified = ack.get("tenant_id") or ""
        record = self.daemon.service._tenants.get(qualified)
        payload = {
            "idempotent_replay": True,
            "route": ack.get("route"),
            "tenant_id": self._unqualify(qualified),
            "uid": ack.get("uid"),
        }
        if ack.get("knobs"):
            payload["knobs"] = ack["knobs"]
        if record is not None:
            payload["status"] = record.status.value
            payload["generations"] = int(record.generations)
        return 200, _JSON, json.dumps(payload), None

    @staticmethod
    def _unqualify(qualified: str) -> str:
        head, sep, tail = qualified.partition(PRINCIPAL_SEP)
        return tail if sep else qualified

    # -- mutating routes -----------------------------------------------------
    def _submit(
        self, principal: str, headers: dict[str, str], body: bytes
    ) -> tuple[int, str, str, "dict[str, str] | None"]:
        key = self._idem_key(principal, headers, required=True)
        payload = self._json_body(body)
        spec = self._decode_submit_spec(payload)
        qualified = self._qualify(principal, spec.tenant_id)
        spec = dataclass_replace(spec, tenant_id=qualified)
        tenant_class = str(payload.get("tenant_class", "standard"))
        with self.lock:
            replay = self._idem_replay(key)
            if replay is not None:
                return replay
            try:
                record = self.daemon.submit(
                    spec,
                    tenant_class=tenant_class,
                    journal_extra={"idem": key.split(":", 1)[1], "principal": principal},
                )
            except AdmissionError as e:
                raise self._admission_error(e) from e
            except ValueError as e:
                raise _ApiError(400, "bad-spec", str(e)) from e
            self._idem[key] = {
                "route": "submit",
                "tenant_id": qualified,
                "uid": record.uid,
            }
            return (
                201,
                _JSON,
                json.dumps(
                    {
                        "tenant_id": self._unqualify(qualified),
                        "uid": int(record.uid),
                        "status": record.status.value,
                        "tenant_class": tenant_class,
                    }
                ),
                None,
            )

    def _steer(
        self,
        principal: str,
        tenant_id: str,
        headers: dict[str, str],
        body: bytes,
    ) -> tuple[int, str, str, "dict[str, str] | None"]:
        key = self._idem_key(principal, headers, required=False)
        payload = self._json_body(body)
        kwargs = {
            k: payload[k]
            for k in ("n_steps", "checkpoint_every", "max_restarts")
            if payload.get(k) is not None
        }
        with self.lock:
            replay = self._idem_replay(key)
            if replay is not None:
                return replay
            record = self._resolve(principal, tenant_id)
            extra = (
                {"idem": key.split(":", 1)[1], "principal": principal}
                if key is not None
                else None
            )
            try:
                knobs = self.daemon.steer(
                    record.spec.tenant_id, journal_extra=extra, **kwargs
                )
            except ValueError as e:
                raise _ApiError(400, "bad-steer", str(e)) from e
            except RuntimeError as e:
                raise _ApiError(409, "not-steerable", str(e)) from e
            except AdmissionError as e:
                raise self._admission_error(e) from e
            if key is not None:
                self._idem[key] = {
                    "route": "steer",
                    "tenant_id": record.spec.tenant_id,
                    "uid": record.uid,
                    "knobs": knobs,
                }
            return (
                200,
                _JSON,
                json.dumps(
                    {
                        "tenant_id": tenant_id,
                        "uid": int(record.uid),
                        "knobs": knobs,
                        "applies": "next segment boundary",
                    }
                ),
                None,
            )

    def _withdraw(
        self, principal: str, tenant_id: str, headers: dict[str, str]
    ) -> tuple[int, str, str, "dict[str, str] | None"]:
        key = self._idem_key(principal, headers, required=False)
        with self.lock:
            replay = self._idem_replay(key)
            if replay is not None:
                return replay
            record = self._resolve(principal, tenant_id)
            try:
                prior = self.daemon.park(record.spec.tenant_id)
            except RuntimeError as e:
                raise _ApiError(409, "not-withdrawable", str(e)) from e
            except AdmissionError as e:
                raise self._admission_error(e) from e
            if key is not None:
                # park() journals an "evict" record without extra fields;
                # the in-memory map still dedups same-process retries, and
                # a post-restart retry of an already-parked tenant gets a
                # truthful 409 (the ack's content, minus the 2xx).
                self._idem[key] = {
                    "route": "withdraw",
                    "tenant_id": record.spec.tenant_id,
                    "uid": record.uid,
                }
            return (
                200,
                _JSON,
                json.dumps(
                    {
                        "tenant_id": tenant_id,
                        "uid": int(record.uid),
                        "was": prior,
                        "status": record.status.value,
                    }
                ),
                None,
            )

    # -- read routes ---------------------------------------------------------
    def _status(
        self, principal: str, tenant_id: str
    ) -> tuple[int, str, str, None]:
        with self.lock:
            record = self._resolve(principal, tenant_id)
            payload = self._snapshot(tenant_id, record)
        return 200, _JSON, json.dumps(payload), None

    def _snapshot(self, tenant_id: str, record: Any) -> dict[str, Any]:
        return {
            "tenant_id": tenant_id,
            "uid": int(record.uid),
            "status": record.status.value,
            "generations": int(record.generations),
            "n_steps": int(record.spec.n_steps),
            "restarts": int(record.restarts),
            "steer": dict(record.steer),
        }

    def _result(
        self, principal: str, tenant_id: str, query: dict[str, str]
    ) -> tuple[int, str, "str | bytes", "dict[str, str] | None"]:
        deadline = time.monotonic() + self._wait(query)
        while True:
            with self.lock:
                record = self._resolve(principal, tenant_id)
                done = record.status is TenantStatus.COMPLETED
                snapshot = self._snapshot(tenant_id, record)
            if done or time.monotonic() >= deadline:
                break
            time.sleep(_POLL_SECONDS)
        if query.get("format") == "npz":
            return self._result_npz(principal, tenant_id, record)
        if not done:
            return 202, _JSON, json.dumps(snapshot), None
        with self.lock:
            history = []
            if record.monitor is not None:
                history = [
                    np.asarray(row).tolist()
                    for row in getattr(record.monitor, "fitness_history", [])
                ]
            snapshot = self._snapshot(tenant_id, record)
        name, digests = self._checkpoint_digests(record)
        snapshot.update(
            {
                "fitness_history": history,
                "checkpoint": name,
                "leaf_digests": digests,
            }
        )
        return 200, _JSON, json.dumps(snapshot), None

    def _result_npz(
        self, principal: str, tenant_id: str, record: Any
    ) -> tuple[int, str, bytes, "dict[str, str] | None"]:
        """The newest checkpoint archive, raw — the client verifies
        bit-identity against a local run from these exact bytes."""
        ns = self.daemon.service.namespace(record.spec.tenant_id)
        names = (
            sorted(p.name for p in ns.glob("*.npz")) if ns.is_dir() else []
        )
        if not names:
            raise _ApiError(
                404,
                "no-checkpoint",
                f"tenant {tenant_id!r} has no published checkpoint yet",
            )
        newest = ns / names[-1]
        return (
            200,
            "application/octet-stream",
            newest.read_bytes(),
            {"X-Checkpoint-Name": names[-1]},
        )

    def _checkpoint_digests(
        self, record: Any
    ) -> tuple[str | None, dict[str, str] | None]:
        from ..utils.checkpoint import read_manifest

        ns = self.daemon.service.namespace(record.spec.tenant_id)
        names = (
            sorted(p.name for p in ns.glob("*.npz")) if ns.is_dir() else []
        )
        if not names:
            return None, None
        try:
            manifest = read_manifest(ns / names[-1])
            return names[-1], dict(manifest.get("leaf_digests") or {})
        except Exception:  # noqa: BLE001 - a torn file is a read-path 404
            return names[-1], None

    def _flight(
        self, principal: str, tenant_id: str, query: dict[str, str]
    ) -> tuple[int, str, str, None]:
        try:
            after = int(query.get("after", -1))
        except ValueError as e:
            raise _ApiError(400, "bad-query", f"after must be an int: {e}")
        deadline = time.monotonic() + self._wait(query)
        while True:
            with self.lock:
                record = self._resolve(principal, tenant_id)
                if record.flight is None:
                    raise _ApiError(
                        404,
                        "no-flight",
                        f"tenant {tenant_id!r} has no flight recorder "
                        f"armed (construct the daemon with "
                        f"obs=Observability(flight=FlightRecorder(...)))",
                    )
                rows = [
                    row
                    for row in record.flight.rows()
                    if row.get("generation", 0) > after
                ]
            if rows or time.monotonic() >= deadline:
                break
            time.sleep(_POLL_SECONDS)
        return (
            200,
            _JSON,
            json.dumps({"tenant_id": tenant_id, "after": after, "rows": rows}),
            None,
        )

    # -- request plumbing ----------------------------------------------------
    @staticmethod
    def _wait(query: dict[str, str]) -> float:
        try:
            wait = float(query.get("wait", 0.0))
        except ValueError as e:
            raise _ApiError(400, "bad-query", f"wait must be seconds: {e}")
        return max(0.0, min(wait, MAX_WAIT_SECONDS))

    @staticmethod
    def _json_body(body: bytes) -> dict[str, Any]:
        if not body:
            return {}
        try:
            payload = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise _ApiError(400, "bad-json", f"request body: {e}") from e
        if not isinstance(payload, dict):
            raise _ApiError(400, "bad-json", "request body must be an object")
        return payload

    def _decode_submit_spec(self, payload: dict[str, Any]) -> Any:
        from .daemon import _decode_spec

        spec_field = payload.get("spec")
        if isinstance(spec_field, dict):
            if spec_field.get("format") != "pickle":
                raise _ApiError(
                    400,
                    "bad-spec",
                    f"unknown spec format {spec_field.get('format')!r} "
                    f"(only 'pickle' — or use the JSON catalog form)",
                )
            try:
                return _decode_spec(str(spec_field.get("blob", "")))
            except Exception as e:  # noqa: BLE001 - hostile blob = 400
                raise _ApiError(
                    400, "bad-spec", f"undecodable spec blob: {e}"
                ) from e
        if "algorithm" in payload and "problem" in payload:
            return self._catalog_spec(payload)
        raise _ApiError(
            400,
            "bad-spec",
            "submit body needs either {'spec': {'format': 'pickle', "
            "'blob': ...}} or the JSON catalog form "
            "({'algorithm': {...}, 'problem': {...}, 'tenant_id', 'n_steps'})",
        )

    def _catalog_spec(self, payload: dict[str, Any]) -> Any:
        """Build a TenantSpec from the curl-friendly JSON catalog form:
        algorithm/problem classes named out of the public registries
        (``evox_tpu.algorithms.__all__`` / ``problems.numerical.__all__``
        — a whitelist, not ``getattr`` on arbitrary modules)."""
        import jax.numpy as jnp

        from .. import algorithms
        from ..problems import numerical
        from .tenant import TenantSpec

        alg_cfg = dict(payload["algorithm"])
        prob_cfg = dict(payload["problem"])
        alg_kind = str(alg_cfg.pop("kind", ""))
        prob_kind = str(prob_cfg.pop("kind", ""))
        if alg_kind not in getattr(algorithms, "__all__", ()):
            raise _ApiError(
                400, "bad-spec", f"unknown algorithm kind {alg_kind!r}"
            )
        if prob_kind not in getattr(numerical, "__all__", ()):
            raise _ApiError(
                400, "bad-spec", f"unknown problem kind {prob_kind!r}"
            )
        try:
            pop_size = int(alg_cfg.pop("pop_size"))
            dim = int(alg_cfg.pop("dim"))
            lb = jnp.full((dim,), float(alg_cfg.pop("lb")))
            ub = jnp.full((dim,), float(alg_cfg.pop("ub")))
            algorithm = getattr(algorithms, alg_kind)(
                pop_size, lb, ub, **alg_cfg
            )
            problem = getattr(numerical, prob_kind)(**prob_cfg)
            return TenantSpec(
                str(payload.get("tenant_id", "")),
                algorithm,
                problem,
                n_steps=int(payload.get("n_steps", 0)),
                uid=(
                    None if payload.get("uid") is None else int(payload["uid"])
                ),
            )
        except _ApiError:
            raise
        except (KeyError, TypeError, ValueError) as e:
            raise _ApiError(
                400, "bad-spec", f"catalog spec: {type(e).__name__}: {e}"
            ) from e

    # -- error + telemetry ---------------------------------------------------
    def _admission_error(self, e: AdmissionError) -> _ApiError:
        seconds = e.retry_after_seconds
        if seconds is None:
            seconds = retry_after_seconds(
                e.retry_after_segments, self.daemon._last_segment_seconds
            )
        status = {
            "shed": 429,
            "queue-full": 503,
            "journal-failed": 503,
            # Router (TenantRouter) refusals: the placement decision is
            # journaled, so a retry lands exactly once — all retryable.
            "member-link": 503,
            "member-down": 503,
            "no-members": 503,
            "id-collision": 409,
            "uid-collision": 409,
            "uid-mismatch": 409,
        }.get(e.reason, 400)
        return _ApiError(
            status,
            e.reason,
            str(e),
            retry_after=seconds if status in (429, 503) else None,
        )

    def _observe(self, route: str, code: int) -> None:
        self._requests[(route, int(code))] = (
            self._requests.get((route, int(code)), 0) + 1
        )
        self._inc(
            "evox_gateway_requests_total",
            "Gateway API requests served, by route and status code.",
            route=route,
            code=str(int(code)),
        )
        slo = getattr(self.daemon, "slo", None)
        if slo is not None:
            try:
                # 4xx is a good event: the service answered correctly.
                slo.record(SIGNAL_GATEWAY, code < 500)
            except Exception:  # pragma: no cover - tracker misconfig
                pass

    def _inc(self, name: str, help: str = "", **labels: str) -> None:
        registry = self.daemon._registry
        if registry is None:
            return
        try:
            registry.counter(name, help, **labels).inc()
        except Exception:  # pragma: no cover - broken registry
            pass

    def statusz_payload(self) -> dict[str, Any]:
        """The ``/statusz`` ``gateway`` section (read-only, fail-safe):
        request/error/retry-after/idempotency counters plus live tenant
        counts per principal (split off the qualified ids)."""
        principals: dict[str, int] = {}
        for tid in list(self.daemon.service._tenants):
            head, sep, _tail = tid.partition(PRINCIPAL_SEP)
            if sep:
                principals[head] = principals.get(head, 0) + 1
        payload = {
            "requests": {
                f"{route}:{code}": n
                for (route, code), n in sorted(self._requests.items())
            },
            "errors": sum(
                n for (_r, code), n in self._requests.items() if code >= 400
            ),
            "auth_rejects": self._auth_rejects,
            "idem_replays": self._idem_replays,
            "retry_after_sent": self._retry_after_sent,
            "idem_keys": len(self._idem),
            "principals": principals,
        }
        if self.chaos is not None:
            try:
                payload["chaos"] = self.chaos.statusz_payload()
            except Exception as e:  # noqa: BLE001 - read-only, fail-safe
                payload["chaos"] = {"error": f"{type(e).__name__}: {e}"}
        return payload
