"""Multi-tenant optimization service: thousands of concurrent runs, one
mesh, per-tenant fault bulkheads.

The serving layer over the fused-segment machinery (ROADMAP item 2):
:class:`TenantPack` steps a compilation bucket's tenants as ONE vmapped
fused segment with lane-granular freeze/evict semantics, and
:class:`OptimizationService` runs the lifecycle around it — bounded-queue
admission control, shape-bucket affinity, boundary-only
admission/retirement (continuous batching), per-tenant PRNG/telemetry/
health/checkpoint isolation, reject-with-reason overload behavior, and
preemption-safe emergency checkpointing of every tenant namespace.

The contract (pinned by ``tests/test_service.py``): a tenant's trajectory
— final state, monitor counters, checkpoint content digests — is
**bit-identical** whether it runs alone or packed beside cotenants that
inject NaNs, stagnate, get evicted, or trigger restarts.
"""

from .pack import TenantPack, assign_fault_lane
from .service import AdmissionError, OptimizationService, ServiceStats
from .tenant import (
    TenantRecord,
    TenantSpec,
    TenantStatus,
    bucket_key,
    static_signature,
)

__all__ = [
    "AdmissionError",
    "OptimizationService",
    "ServiceStats",
    "TenantPack",
    "TenantRecord",
    "TenantSpec",
    "TenantStatus",
    "assign_fault_lane",
    "bucket_key",
    "static_signature",
]
