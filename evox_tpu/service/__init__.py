"""Multi-tenant optimization service: thousands of concurrent runs, one
mesh, per-tenant fault bulkheads.

The serving layer over the fused-segment machinery (ROADMAP item 2):
:class:`TenantPack` steps a compilation bucket's tenants as ONE vmapped
fused segment with lane-granular freeze/evict semantics, and
:class:`OptimizationService` runs the lifecycle around it — bounded-queue
admission control, shape-bucket affinity, boundary-only
admission/retirement (continuous batching), per-tenant PRNG/telemetry/
health/checkpoint isolation, reject-with-reason overload behavior, and
preemption-safe emergency checkpointing of every tenant namespace.

The contract (pinned by ``tests/test_service.py``): a tenant's trajectory
— final state, monitor counters, checkpoint content digests — is
**bit-identical** whether it runs alone or packed beside cotenants that
inject NaNs, stagnate, get evicted, or trigger restarts.

:class:`ServiceDaemon` (PR 11) is the durable lifecycle around the
service: every submission is journaled (:class:`RequestJournal` —
crash-safe, checksummed, at-least-once replay), the packed segment
programs persist across restarts (zero cold-start via
:class:`~evox_tpu.utils.ExecutableCache`), and admission is SLO-aware
(per-class budgets, load shedding with structured retry-after hints,
brown-out cadence stretching) — kill the daemon at any point and a
restart reconstructs the exact service state with no lost acknowledged
work and no XLA compile on the hot path.

:class:`Gateway` + :class:`GatewayClient` (PR 16) are the network front
door on the daemon's endpoint plane: authenticated submit/steer/withdraw/
fetch over HTTP where every mutating reply is sent only after the journal
append, client idempotency keys ride the journal for exactly-once
admission across retries AND daemon restarts, bearer-token principals
namespace tenant ids (and thus checkpoint/flight directories), and
overload maps to 429/503 with ``Retry-After`` from the live measured
segment cadence — chaos-tested by
:class:`~evox_tpu.resilience.FaultyTransport` and a kill-at-every-
boundary HTTP matrix.

:class:`TenantRouter` + :class:`ServiceMember` (PR 17) are the
cross-host scheduler over the same planes: per-host daemons advertise
capacity (free lanes per bucket, queue depths, cadence, cache warmth)
through their :class:`~evox_tpu.parallel.HostHeartbeat` payloads, the
router places each submit by bucket affinity and journals every
placement as a ``kind="placement"`` record BEFORE acking (router
SIGKILL+restart replays to the same placement map; the gateway's
idempotency keys ride the router journal end-to-end), dead members'
tenants migrate onto survivors bit-identically via their checkpoint
namespaces, member-link chaos degrades to structured 503 +
``Retry-After``, and a journaled ``autoscale`` decider
(:func:`~evox_tpu.control.decide_autoscale`) drains-then-retires idle
members and requests growth under shed pressure or SLO burn.
"""

from .client import GatewayClient, GatewayError, HttpTransport, encode_spec
from .daemon import STEER_KNOBS, DaemonStats, ServiceDaemon, TenantClass
from .gateway import Gateway
from .journal import JournalDamage, JournalError, JournalRecord, RequestJournal
from .member import MEMBER_API_PREFIX, ServiceMember
from .router import TenantRouter
from .pack import TenantPack, assign_fault_lane
from .service import (
    AdmissionError,
    OptimizationService,
    Rejection,
    ServiceStats,
    retry_after_seconds,
)
from .tenant import (
    TenantRecord,
    TenantSpec,
    TenantStatus,
    bucket_key,
    static_signature,
    validate_tenant_id,
)

__all__ = [
    "AdmissionError",
    "DaemonStats",
    "Gateway",
    "GatewayClient",
    "GatewayError",
    "HttpTransport",
    "MEMBER_API_PREFIX",
    "STEER_KNOBS",
    "JournalDamage",
    "JournalError",
    "JournalRecord",
    "OptimizationService",
    "Rejection",
    "RequestJournal",
    "ServiceDaemon",
    "ServiceMember",
    "ServiceStats",
    "TenantClass",
    "TenantPack",
    "TenantRecord",
    "TenantRouter",
    "TenantSpec",
    "TenantStatus",
    "assign_fault_lane",
    "bucket_key",
    "encode_spec",
    "retry_after_seconds",
    "static_signature",
    "validate_tenant_id",
]
