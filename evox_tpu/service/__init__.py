"""Multi-tenant optimization service: thousands of concurrent runs, one
mesh, per-tenant fault bulkheads.

The serving layer over the fused-segment machinery (ROADMAP item 2):
:class:`TenantPack` steps a compilation bucket's tenants as ONE vmapped
fused segment with lane-granular freeze/evict semantics, and
:class:`OptimizationService` runs the lifecycle around it — bounded-queue
admission control, shape-bucket affinity, boundary-only
admission/retirement (continuous batching), per-tenant PRNG/telemetry/
health/checkpoint isolation, reject-with-reason overload behavior, and
preemption-safe emergency checkpointing of every tenant namespace.

The contract (pinned by ``tests/test_service.py``): a tenant's trajectory
— final state, monitor counters, checkpoint content digests — is
**bit-identical** whether it runs alone or packed beside cotenants that
inject NaNs, stagnate, get evicted, or trigger restarts.

:class:`ServiceDaemon` (PR 11) is the durable lifecycle around the
service: every submission is journaled (:class:`RequestJournal` —
crash-safe, checksummed, at-least-once replay), the packed segment
programs persist across restarts (zero cold-start via
:class:`~evox_tpu.utils.ExecutableCache`), and admission is SLO-aware
(per-class budgets, load shedding with structured retry-after hints,
brown-out cadence stretching) — kill the daemon at any point and a
restart reconstructs the exact service state with no lost acknowledged
work and no XLA compile on the hot path.
"""

from .daemon import DaemonStats, ServiceDaemon, TenantClass
from .journal import JournalDamage, JournalError, JournalRecord, RequestJournal
from .pack import TenantPack, assign_fault_lane
from .service import (
    AdmissionError,
    OptimizationService,
    Rejection,
    ServiceStats,
)
from .tenant import (
    TenantRecord,
    TenantSpec,
    TenantStatus,
    bucket_key,
    static_signature,
)

__all__ = [
    "AdmissionError",
    "DaemonStats",
    "JournalDamage",
    "JournalError",
    "JournalRecord",
    "OptimizationService",
    "Rejection",
    "RequestJournal",
    "ServiceDaemon",
    "ServiceStats",
    "TenantClass",
    "TenantPack",
    "TenantRecord",
    "TenantSpec",
    "TenantStatus",
    "assign_fault_lane",
    "bucket_key",
    "static_signature",
]
