"""Tenant model for the multi-tenant optimization service.

A **tenant** is one independent optimization run a user submitted: an
algorithm configuration, a problem, a generation budget, and a stable
identity.  The service packs tenants whose compiled program would be
identical — same algorithm class and static configuration, same
``(pop, dim)`` shape, same problem program — into one **bucket**, and steps
every tenant of a bucket as one vmapped fused segment
(:class:`~evox_tpu.service.TenantPack`).

Identity discipline (the bulkhead contract leans on it):

* ``uid`` — a stable non-negative integer, assigned at first submission and
  kept across eviction/readmission.  It seeds the tenant's PRNG stream
  (``fold_in(service_key, uid)`` — *identity*-keyed, never lane-keyed, the
  same topology-invariance discipline GL006 enforces for shard streams), it
  is the monitor ``instance_id`` every history payload carries, and it is
  the ``fault_lane`` value tenant-keyed chaos schedules match on.  Lane
  *position* is a placement detail that may change on every readmission and
  must never influence a value.
* ``bucket_key`` — the compilation-shape identity: two tenants share a
  bucket only when their algorithm/problem static configuration digests are
  equal, so one traced program is exact for every lane.  Over-splitting is
  always safe (a lonely tenant just gets its own pack); under-splitting
  never happens silently.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass, field
from enum import Enum
from typing import Any

import numpy as np

__all__ = [
    "TenantSpec",
    "TenantStatus",
    "TenantRecord",
    "bucket_key",
    "static_signature",
    "validate_tenant_id",
]

#: Upper bound on tenant id length: the id is a directory component of the
#: checkpoint namespace and a flight-bundle path, and most filesystems cap
#: components at 255 bytes — leave room for ``ckpt_########.npz`` siblings
#: and principal prefixes.
MAX_TENANT_ID_LEN = 128


def validate_tenant_id(tenant_id: Any) -> str:
    """Validate one externally-supplied tenant id as a **safe path
    component** — the id names the tenant's checkpoint namespace directory
    (``<root>/tenants/<id>/``) and its flight-bundle paths, so this is the
    single choke point every id passes before it can touch a filesystem
    path: :class:`TenantSpec` construction, the service's
    :meth:`~evox_tpu.service.OptimizationService.namespace`, and the
    network gateway (which maps the :class:`ValueError` to a structured
    400) all call it.

    Rejects (``ValueError``): non-strings, empty ids, anything outside
    ``[A-Za-z0-9._-]`` (separators, traversal slashes, ``%``-escapes,
    NULs...), the dot-only ids ``"."``/``".."``/``"..."``... (every
    dot-only string — ``.`` and ``..`` are path navigation, and keeping
    the whole family out is cheaper than reasoning about each), and ids
    longer than ``MAX_TENANT_ID_LEN``.  Returns the id unchanged."""
    if not isinstance(tenant_id, str) or not re.fullmatch(
        r"[A-Za-z0-9._-]+", tenant_id or ""
    ):
        raise ValueError(
            f"tenant_id must be a non-empty [A-Za-z0-9._-] string (it "
            f"names the tenant's checkpoint namespace directory), got "
            f"{tenant_id!r}"
        )
    if set(tenant_id) == {"."}:
        raise ValueError(
            f"tenant_id {tenant_id!r} is a dot-only path component "
            f"('.'/'..' are directory navigation, not names)"
        )
    if len(tenant_id) > MAX_TENANT_ID_LEN:
        raise ValueError(
            f"tenant_id is {len(tenant_id)} chars; max is "
            f"{MAX_TENANT_ID_LEN} (it becomes a filesystem path component)"
        )
    return tenant_id


class TenantStatus(Enum):
    """Lifecycle of one tenant inside the service.

    ``QUEUED`` — admitted to the bounded queue, waiting for a lane.
    ``RUNNING`` — occupying a live pack lane.
    ``QUARANTINED`` — its lane is frozen (health verdict after the restart
    budget, or an in-scan early stop): the state stops evolving, cotenants
    are untouched, and the tenant stays resumable from its checkpoints.
    ``EVICTED`` — checkpointed to its namespace and removed from its lane
    (operator decision / preemption); readmission resumes bit-identically.
    ``COMPLETED`` — generation budget reached; final state retrievable.
    """

    QUEUED = "queued"
    RUNNING = "running"
    QUARANTINED = "quarantined"
    EVICTED = "evicted"
    COMPLETED = "completed"


@dataclass
class TenantSpec:
    """What a user submits: one independent optimization run.

    :param tenant_id: caller-chosen name; also the tenant's checkpoint
        namespace directory (restricted to ``[A-Za-z0-9._-]`` so it is a
        safe path component).
    :param algorithm: the algorithm instance (its static configuration
        keys the bucket; evolving values live in per-tenant state).
    :param problem: the problem instance.  The FIRST tenant of a bucket
        donates the actual traced objects (the bucket template); later
        tenants' objects must be configuration-equal (enforced via
        :func:`bucket_key`) and are used for bucketing only.
    :param n_steps: generation budget.  Generations advance in the
        service's fixed segment length, so completion lands on the first
        segment boundary at or past the budget (continuous-batching
        quantization — the same rounding for every tenant, solo or
        packed).
    :param uid: optional explicit stable identity (see the module
        docstring); auto-assigned by submission order when ``None``.
        Supply it when a bit-exact cross-service comparison (the bulkhead
        tests) needs the same tenant identity in two service instances.
    :param workload: ``"standard"`` (an ordinary optimization run) or
        ``"hpo"`` (a meta-optimization run: ``problem`` must be — or
        wrap — an :class:`~evox_tpu.hpo.NestedProblem`, whose fused
        nested evaluate packs like any other program).  HPO tenants get
        per-tenant ``evox_hpo_*`` metrics and, with ``grow=``, the
        elastic inner-population ladder.
    :param grow: optional :class:`~evox_tpu.hpo.GrowthLadder` for
        ``workload="hpo"`` tenants — when the service carries a
        :class:`~evox_tpu.control.Controller`, inner-run stagnation
        trends fire journaled ``hpo-grow`` decisions that regrow this
        tenant's inner population (bucket re-key + lane surgery at a
        segment boundary).
    :param solution_transform: optional solution transform for the
        tenant's workflow (``StdWorkflow(solution_transform=)``) — HPO
        tenants use it to map outer solution vectors onto the inner
        hyper-parameter dict.  Part of the compiled program, so it
        participates in the bucket key (by function code + closure
        digest); must be a module-level function (not a lambda) for
        daemon journal durability.
    :param precision: optional
        :class:`~evox_tpu.precision.PrecisionPolicy` this tenant's
        workflow runs under.  Policy identity is part of the bucket key —
        a bf16 tenant and an f32 tenant trace different programs (the
        state avals differ) and must never share a vmapped pack.
    :param key_impl: optional PRNG key implementation for the tenant's
        identity-keyed stream (``"rbg"`` for the partitionable hardware
        generator).  Also part of the bucket key: key-data shapes differ
        per impl, so an rbg tenant and a threefry tenant cannot share a
        lane axis — and must not share a stream family either.
    """

    tenant_id: str
    algorithm: Any
    problem: Any
    n_steps: int
    uid: int | None = None
    workload: str = "standard"
    grow: Any = None
    solution_transform: Any = None
    precision: Any = None
    key_impl: str | None = None

    def __post_init__(self) -> None:
        validate_tenant_id(self.tenant_id)
        if self.n_steps < 1:
            raise ValueError(f"n_steps must be >= 1, got {self.n_steps}")
        if self.uid is not None and self.uid < 0:
            raise ValueError(f"uid must be >= 0, got {self.uid}")
        if self.workload not in ("standard", "hpo"):
            raise ValueError(
                f"workload must be 'standard' or 'hpo', got "
                f"{self.workload!r}"
            )
        if self.workload == "hpo":
            # Duck-typed (not an isinstance) so wrapper chains — fault
            # injection around the nested problem — stay admissible; the
            # marker is NestedProblem's class attribute.
            from ..hpo.nested import find_nested

            nested = find_nested(self.problem)
            if nested is None:
                raise ValueError(
                    "workload='hpo' needs a problem whose chain contains "
                    "an evox_tpu.hpo.NestedProblem (the fused nested "
                    "evaluate is what the HPO workload packs)"
                )
            if self.grow is not None:
                from ..hpo.elastic import validate_ladder_window

                validate_ladder_window(self.grow, nested)
        elif self.grow is not None:
            raise ValueError(
                "grow= (the elastic inner-population ladder) only applies "
                "to workload='hpo' tenants"
            )
        if self.key_impl is not None:
            from ..precision import resolve_key_impl

            # Normalize at submission so the bucket key and every stream
            # derivation agree on one canonical name.
            self.key_impl = resolve_key_impl(self.key_impl)


@dataclass
class TenantRecord:
    """The service's runtime record of one tenant (host-side bookkeeping;
    every evolving *value* lives in the tenant's lane state)."""

    spec: TenantSpec
    uid: int
    status: TenantStatus = TenantStatus.QUEUED
    bucket: tuple | None = None
    lane: int | None = None
    generations: int = 0
    restarts: int = 0
    # Elastic inner-population growths applied to an HPO tenant (the
    # deterministic-regrow salt index; bounded by the service's
    # max_restarts budget alongside restarts).
    grows: int = 0
    segments_since_checkpoint: int = 0
    # Human-readable lifecycle trail: admissions, verdicts, restarts,
    # evictions — the per-tenant analogue of RunStats.failures.
    events: list[str] = field(default_factory=list)
    monitor: Any | None = None
    result: Any | None = None
    # Per-tenant flight recorder (``FlightRecorder.for_tenant``): fed from
    # the pack's lane-demuxed flight telemetry, dumps postmortem bundles
    # into the tenant's own namespace on tenant-warning bus events.
    flight: Any | None = None
    # Per-tenant scheduling-knob overrides applied by a journaled daemon
    # ``steer`` record at a segment boundary: ``max_restarts`` /
    # ``checkpoint_every`` here shadow the service-wide values for THIS
    # tenant (budget changes rewrite ``spec.n_steps`` directly).  Values
    # only, never state: steering affects when the scheduler acts, not
    # what any lane computes.
    steer: dict[str, int] = field(default_factory=dict)


def _hash_code(h: "hashlib._Hash", code: Any) -> None:
    """Digest of a code object's behavior: bytecode alone is NOT enough —
    constants and attribute/global names are referenced by index, so two
    functions differing only in a string constant (e.g. which Parameter
    path a solution transform writes) share identical ``co_code``.  Hash
    names and constants too, recursing into nested code objects
    (lambdas/compehensions defined inside the function)."""
    h.update(code.co_code)
    h.update(repr(code.co_names).encode())
    for const in code.co_consts:
        if hasattr(const, "co_code"):
            _hash_code(h, const)
        else:
            h.update(repr(const).encode())


def _hash_value(h: "hashlib._Hash", value: Any) -> None:
    if isinstance(value, (bool, int, float, str, bytes, type(None))):
        h.update(repr(value).encode())
    elif callable(value) and hasattr(value, "__code__"):
        # Plain functions (solution transforms, growth factories): hash
        # by identity-of-behavior — qualified name + code digest (byte
        # code AND names/constants) + closure contents — so two tenants
        # with different transforms can never silently share a bucket,
        # while re-imports of the same function (daemon journal replay in
        # a fresh process) hash identically.
        h.update(getattr(value, "__qualname__", "<fn>").encode())
        _hash_code(h, value.__code__)
        for cell in value.__closure__ or ():
            try:
                _hash_value(h, cell.cell_contents)
            except ValueError:  # empty cell
                h.update(b"<empty-cell>")
    elif isinstance(value, (tuple, list, frozenset, set)):
        h.update(b"(")
        for item in sorted(value, key=repr) if isinstance(
            value, (set, frozenset)
        ) else value:
            _hash_value(h, item)
        h.update(b")")
    elif isinstance(value, dict):
        h.update(b"{")
        for k in sorted(value, key=repr):
            _hash_value(h, k)
            _hash_value(h, value[k])
        h.update(b"}")
    elif hasattr(value, "dtype") and hasattr(value, "shape"):
        arr = np.asarray(value)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    elif hasattr(value, "__dict__") or hasattr(value, "evaluate") or hasattr(
        value, "step"
    ):
        # Nested component (a problem wrapper chain, an inner optimizer):
        # recurse into its static configuration.
        h.update(type(value).__name__.encode())
        _hash_attrs(h, value)
    else:
        # Opaque object: type identity only.  Conservative — two tenants
        # holding distinct opaque objects of one type bucket together only
        # if everything else matches; the traced template then defines the
        # program, which is exactly the sharing the bucket promises.
        h.update(type(value).__name__.encode())


# Runtime-volatile component attributes that must not split buckets (or
# drift a tenant's bucket between submissions): trace-time flags the
# workflow toggles, host-side fault counters.
_VOLATILE_ATTRS = frozenset(
    {"in_sharded_program", "in_fused_program", "deadline_trips"}
)


def _hash_attrs(h: "hashlib._Hash", obj: Any) -> None:
    attrs = getattr(obj, "__dict__", None)
    if not attrs:
        return
    for name in sorted(attrs):
        if name.startswith("_") or name in _VOLATILE_ATTRS:
            continue
        h.update(name.encode())
        _hash_value(h, attrs[name])


def static_signature(obj: Any) -> str:
    """Digest of a component's static (public, non-volatile)
    configuration — attribute names and values, arrays by bytes, nested
    components recursively.  Two components with equal signatures trace
    the same program modulo the values that live in per-tenant state."""
    h = hashlib.sha256()
    h.update(type(obj).__name__.encode())
    _hash_attrs(h, obj)
    return h.hexdigest()


def bucket_key(spec: TenantSpec) -> tuple:
    """The compilation-shape bucket a tenant belongs to: algorithm class +
    ``(pop, dim)`` + the static-configuration digests of algorithm,
    problem, and solution transform, plus the tenant's **numerics
    identity** (precision-policy identity and PRNG key implementation —
    both change the traced program's avals, so sharing a bucket across
    them would stack mismatched dtypes/key-data shapes onto one lane
    axis).  Tenants sharing a key are safe to step through ONE traced
    program with per-tenant state."""
    from ..precision import precision_identity, resolve_key_impl

    algo = spec.algorithm
    if spec.solution_transform is None:
        transform = "no-transform"
    else:
        h = hashlib.sha256()
        _hash_value(h, spec.solution_transform)
        transform = h.hexdigest()
    return (
        type(algo).__name__,
        int(getattr(algo, "pop_size", 0)),
        int(getattr(algo, "dim", 0)),
        type(spec.problem).__name__,
        static_signature(algo),
        static_signature(spec.problem),
        transform,
        precision_identity(spec.precision),
        resolve_key_impl(spec.key_impl),
    )
