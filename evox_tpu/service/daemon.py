"""The durable serving daemon: a service you can kill at any point.

:class:`ServiceDaemon` composes the multi-tenant
:class:`~evox_tpu.service.OptimizationService` (PR 8) with three durability
planes so that a long-lived serving process *survives its own death*:

1. **Crash-safe request journal** (:class:`~evox_tpu.service.RequestJournal`)
   — every submit/evict/retire/complete/preempt is an atomic, fsync'd,
   checksummed record appended *before* the operation is acknowledged.  A
   daemon SIGKILLed at any lifecycle point restarts by replaying the
   journal: the trusted prefix reconstructs the exact set of live tenants
   (at-least-once, deduped by uid), each tenant's checkpoint namespace
   supplies the values, and the run continues bit-identically (minus
   preemption counters) — ``tests/test_daemon.py`` pins the full
   kill-at-every-boundary matrix.

2. **Zero cold-start executable cache**
   (:class:`~evox_tpu.utils.ExecutableCache`) — the packed segment and
   init programs are AOT-compiled once per bucket shape and persisted via
   ``jax.experimental.serialize_executable``; a restarted daemon (or a new
   tenant landing in a declared bucket) loads the executable instead of
   compiling, so the first segment after a restart dispatches in
   milliseconds (``tools/bench_daemon.py`` gates this with a
   ``CompileSentinel``: zero segment compiles on a warm restart).
   Corrupt, stale, or wrong-topology entries are quarantined
   ``*.corrupt`` and recompiled — never trusted.  Optionally jax's own
   persistent compilation cache is pointed at ``<root>/xla_cache`` for the
   long tail of small programs (probe scans, lane surgery).

3. **SLO-aware admission and degradation** — the bounded queue is split
   into per-:class:`TenantClass` budgets; a submission past its class
   budget is **shed** with a structured
   ``AdmissionError(reason="shed", retry_after_segments=...)`` hint
   (computed from the live scheduler state) instead of degrading admitted
   tenants.  Before refusing work, the daemon can **brown out**: when
   queue pressure crosses ``brownout_threshold`` it stretches the segment
   cadence by ``brownout_factor`` (both cadences pre-warmed — no compile),
   trading boundary-work overhead for throughput; hysteresis returns the
   cadence to normal when pressure halves.  Admitted tenants' per-tenant
   gen/s stays within the bulkhead contract throughout (overload
   acceptance in ``tools/bench_daemon.py``).

Under a :class:`~evox_tpu.resilience.FleetSupervisor`, the daemon is the
worker: :meth:`fleet_supervisor` builds a supervisor whose relaunched
workers replay the shared journal and resume every tenant's namespace on
the surviving fleet — host loss becomes tenant migration.
"""

from __future__ import annotations

import base64
import pickle
import time
import warnings
from dataclasses import dataclass, field, replace as dataclass_replace
from pathlib import Path
from typing import Any, Callable, Sequence, Union

from ..obs.aggregate import FleetAggregator
from ..obs.endpoint import IntrospectionEndpoint
from ..obs.metrics import MetricsRegistry
from ..obs.slo import (
    SIGNAL_ADMISSION,
    SIGNAL_RECOVERY,
    SIGNAL_SEGMENT_SECONDS,
    SIGNAL_TENANT_GENS,
    SLOTracker,
)
from ..obs.version import OBS_SCHEMA_VERSION
from ..resilience.preemption import Preempted, PreemptionGuard
from ..utils.checkpoint import CheckpointStore, ReadOnlyCheckpointStore
from ..utils.exec_cache import ExecutableCache, enable_xla_compilation_cache
from .journal import JournalError, RequestJournal
from .service import (
    AdmissionError,
    OptimizationService,
    retry_after_seconds,
)
from .tenant import TenantRecord, TenantSpec, TenantStatus

__all__ = [
    "ServiceDaemon",
    "TenantClass",
    "DaemonStats",
    "STEER_KNOBS",
    "fold_daemon_records",
]

#: The journaled ``steer`` record's adjustable scheduling knobs: the
#: tenant's generation budget, checkpoint cadence, and restart budget.
#: Values only — steering changes when the scheduler acts on a tenant,
#: never what any lane computes, which is why a replayed steer is
#: bit-identical by construction.
STEER_KNOBS = ("n_steps", "checkpoint_every", "max_restarts")


@dataclass(frozen=True)
class TenantClass:
    """One admission class: its share of the bounded queue.

    :param name: class label (the ``tenant_class=`` a submission names).
    :param queue_budget: how many submissions of this class may wait for
        a lane at once; the next one is shed with a retry-after hint.
    :param sheddable: whether overload sheds this class at its budget
        (``False`` reserves shedding for the hard service queue bound —
        e.g. an internal maintenance class).
    """

    name: str
    queue_budget: int
    sheddable: bool = True

    def __post_init__(self) -> None:
        if self.queue_budget < 0:
            raise ValueError(
                f"queue_budget must be >= 0, got {self.queue_budget}"
            )


@dataclass
class DaemonStats:
    """Observable record of what the daemon (beyond the service) did."""

    replayed_records: int = 0
    replayed_tenants: int = 0
    journal_damage: list[str] = field(default_factory=list)
    journal_append_failures: int = 0
    # Wall seconds of the last cold-start recovery (journal replay +
    # tenant resubmission) — the recovery-time SLO's signal.
    replay_seconds: float | None = None
    compactions: int = 0
    compaction_failures: int = 0
    sheds: int = 0
    brownout_entries: int = 0
    brownout_exits: int = 0
    # prewarm results: {program_label: loaded_from_cache}
    prewarmed: dict[str, bool] = field(default_factory=dict)


def _encode_spec(spec: TenantSpec) -> str:
    return base64.b64encode(pickle.dumps(spec)).decode("ascii")


def _decode_spec(blob: str) -> TenantSpec:
    return pickle.loads(base64.b64decode(blob.encode("ascii")))


def _bucket_label(key: tuple) -> str:
    # algorithm[popxdim] + the two static-config digest prefixes: stable
    # across processes, short enough for an exec-cache entry label.
    return f"{key[0]}[{key[1]}x{key[2]}]{key[4][:8]}{key[5][:8]}"


def fold_daemon_records(
    records: Sequence[Any], base: dict[str, Any] | None = None
) -> tuple[dict[str, Any], list[str]]:
    """Pure fold of a daemon journal record stream onto an optional
    snapshot base state; returns ``(state, anomalies)``.

    This single function is both replay's fold (:meth:`ServiceDaemon.start`
    seeds from ``journal.snapshot_state`` and folds the suffix) and
    compaction's (:meth:`RequestJournal.compact` folds the whole history
    into the next snapshot), which makes the replay-equivalence invariant
    hold *by construction*: a snapshot-anchored cold start computes
    exactly the state a full replay would.

    ``state`` is canonical-JSON-serializable (uid keys are strings; set
    members are sorted lists): ``live`` maps uid → the newest submit
    record's data verbatim (spec blob, class, idempotency fields — the
    gateway's exactly-once map survives compaction through it), plus
    ``parked`` / ``completed`` uid lists, ``steers`` (folded knob values,
    last-wins), and ``idem`` (the gateway dedup entries for *all* record
    kinds, so a retried steer or park straddling a compaction still
    replays its ack instead of re-acting).  At-least-once semantics are
    the journal's: duplicates collapse, last state wins.  ``anomalies``
    are human-readable fold warnings (orphan steers) for the caller's
    event stream — never part of the state."""
    base = base or {}
    live: dict[str, dict[str, Any]] = {
        str(k): dict(v) for k, v in (base.get("live") or {}).items()
    }
    parked: set[str] = {str(u) for u in (base.get("parked") or [])}
    completed: set[str] = {str(u) for u in (base.get("completed") or [])}
    steers: dict[str, dict[str, int]] = {
        str(k): dict(v) for k, v in (base.get("steers") or {}).items()
    }
    idem: dict[str, dict[str, Any]] = {
        str(k): dict(v) for k, v in (base.get("idem") or {}).items()
    }
    anomalies: list[str] = []
    for rec in records:
        data = rec.data
        key = data.get("idem")
        principal = data.get("principal")
        if key and principal:
            # Mirrors Gateway._rebuild_idem exactly — the snapshot must
            # preserve the dedup map a full-journal replay would build.
            idem[f"{principal}:{key}"] = {
                "route": rec.kind,
                "tenant_id": data.get("tenant_id"),
                "uid": data.get("uid"),
                "knobs": {
                    k: data[k]
                    for k in STEER_KNOBS
                    if rec.kind == "steer" and k in data
                },
            }
        uid = data.get("uid")
        if uid is None:
            continue
        uid = str(int(uid))
        if rec.kind == "submit":
            live[uid] = dict(data)
            parked.discard(uid)
            # A re-submit after a journaled completion (readmission with
            # a refreshed budget) re-arms the completion record, exactly
            # like the live submit() path.  It also supersedes any
            # earlier steering — the fresh spec carries the caller's
            # current intent.
            completed.discard(uid)
            steers.pop(uid, None)
        elif rec.kind == "evict":
            parked.add(uid)
        elif rec.kind == "retire":
            live.pop(uid, None)
            parked.discard(uid)
            completed.discard(uid)
            steers.pop(uid, None)
        elif rec.kind == "complete":
            # Stays live: resubmission materializes the final result
            # from the namespace without occupying a lane.
            completed.add(uid)
        elif rec.kind == "steer":
            if uid in live:
                # At-least-once: duplicate steer records collapse (last
                # value per knob wins, same as replaying in sequence).
                steers.setdefault(uid, {}).update(
                    {
                        k: int(data[k])
                        for k in STEER_KNOBS
                        if data.get(k) is not None
                    }
                )
            else:
                # A steer can only follow the submit that admitted its
                # tenant — anything else in the stream is journal damage
                # or a spliced tail; skip it loudly.
                anomalies.append(
                    f"steer record #{rec.seq} targets uid {uid} with no "
                    f"live submit before it; skipped"
                )
    return {
        "live": live,
        "parked": sorted(parked, key=int),
        "completed": sorted(completed, key=int),
        "steers": steers,
        "idem": idem,
    }, anomalies


class ServiceDaemon:
    """Durable, SLO-aware lifecycle around an
    :class:`~evox_tpu.service.OptimizationService`.

    Usage::

        daemon = ServiceDaemon("svc_root", lanes_per_pack=64,
                               segment_steps=16, seed=0,
                               prewarm=[example_spec])
        daemon.start()                    # replay journal + pre-warm
        daemon.submit(TenantSpec("alice-1", PSO(...), Ackley(),
                                 n_steps=400))
        daemon.run()                      # drain; Preempted on SIGTERM
        # ... SIGKILL at ANY point above, then, in a fresh process:
        daemon = ServiceDaemon("svc_root", ...)   # same configuration
        daemon.start()                    # replays → same tenants, zero
        daemon.run()                      # compiles, bit-identical states

    :param root: daemon directory — the service root (tenant namespaces
        under ``tenants/``), the journal (``journal.jsonl``), and the
        executable cache (``exec_cache/``) all live under it; sharing it
        across processes/restarts IS the durability contract.
    :param classes: admission classes; default one ``"standard"`` class
        holding the whole ``max_queue``.  Budgets beyond ``max_queue``
        are still bounded by the service queue.
    :param exec_cache: ``True`` (default) builds the persistent cache at
        ``<root>/exec_cache``; an :class:`~evox_tpu.utils.ExecutableCache`
        uses the caller's; ``False``/``None`` disables persistence (AOT
        pre-warm still runs in-process).
    :param xla_cache: additionally point jax's persistent compilation
        cache at ``<root>/xla_cache`` (covers programs nobody pre-warms).
    :param prewarm: the declared bucket grid — example
        :class:`~evox_tpu.service.TenantSpec` instances (never admitted;
        shapes only) whose buckets :meth:`start` pre-warms so the first
        real tenant of each bucket never compiles.
    :param brownout_threshold: queue-pressure fraction
        (``queued / max_queue``) at which the daemon stretches segment
        cadence; ``None`` disables brown-out.
    :param brownout_factor: cadence multiplier under brown-out (both
        cadences are pre-warmed).
    :param store: the :class:`~evox_tpu.utils.CheckpointStore` shared by
        service checkpoints, journal, and executable cache
        (chaos-injectable).
    :param primary: whether this process owns the root (single-writer
        discipline, as in the fleet runner).  Non-primary daemons get a
        read-only store: journal appends raise (submissions belong on the
        primary), checkpoint/exec-cache writes are refused cleanly.
    :param preemption: as the service's — default ``True`` (the daemon
        exists to be supervised); :class:`Preempted` is journaled before
        it propagates.
    :param controller: optional
        :class:`~evox_tpu.control.Controller` closing the loop over the
        daemon: brown-out entry/exit runs on the controller's journaled
        hysteresis instead of the ad-hoc flag check (the daemon's
        ``brownout_threshold`` stays the entry pressure unless the
        controller overrides it), shed thresholds are recomputed from
        the live measured segment cadence when the controller carries an
        ``slo_wait_seconds`` target, and the controller is handed down
        to the :class:`~evox_tpu.service.OptimizationService` for
        per-tenant trend verdicts.  Every decision is appended to THIS
        daemon's request journal (kind ``"decision"``, advisory — a
        failed append warns, the decision still applies) unless the
        controller already carries its own journal; replay reproduces
        the decision sequence bit-for-bit from the journaled evidence
        (``tests/test_control.py``).  Decision records carry no ``uid``,
        so :meth:`start`'s tenant fold skips them by construction.
    :param slos: declarative service-level objectives — a sequence of
        :class:`~evox_tpu.obs.SLO` (or a pre-built
        :class:`~evox_tpu.obs.SLOTracker`).  The daemon feeds them live:
        round wall seconds score the latency objectives, per-running-
        tenant generation throughput the gen/s floors, and every
        admission/shed the availability objectives; burn-rate and
        error-budget gauges (``evox_slo_*``) publish each round.  When a
        ``controller`` is attached the tracker is handed to it (first
        binder wins): burn rates become the journaled evidence behind
        brown-out entry (``Controller(brownout_burn=)``) and exhausted
        budgets halve the class shed thresholds.
    :param endpoint: arm the live introspection endpoint
        (:class:`~evox_tpu.obs.IntrospectionEndpoint`): an ``int`` binds
        that TCP port, ``True`` an OS-assigned one (``daemon.endpoint.url``
        after :meth:`start`).  Serves ``/metrics`` (fleet-aggregated
        when ``<root>/heartbeats`` carries beats, process-local
        otherwise), ``/healthz`` (non-200 on a dead/wedged/slow host
        verdict), ``/statusz`` (tenants, per-class queue depths,
        decision tail, exec-cache hit rates, SLO standings), and
        ``/flightz/<tenant_id>`` (the tenant's flight ring).  Read-only
        and fail-safe: a handler exception is a 500 response, never a
        touched serving path.
    :param endpoint_host: bind address (default loopback).
    :param fleet_dead_after: heartbeat staleness (seconds) after which
        the endpoint's fleet view declares a host dead (``/healthz``
        non-200, its ``/metrics`` series marked ``stale="true"``).
    :param service_kwargs: everything else
        (:class:`~evox_tpu.service.OptimizationService` surface:
        ``health``, ``max_restarts``, ``checkpoint_every``,
        ``monitor_factory``, ``early_stop``, ``obs`` ...).
    """

    JOURNAL_NAME = "journal.jsonl"
    EXEC_CACHE_DIR = "exec_cache"
    XLA_CACHE_DIR = "xla_cache"

    def __init__(
        self,
        root: Union[str, Path],
        *,
        lanes_per_pack: int = 8,
        segment_steps: int = 16,
        max_queue: int = 256,
        seed: int = 0,
        classes: Sequence[TenantClass] | None = None,
        exec_cache: Union[ExecutableCache, bool, None] = True,
        xla_cache: bool = False,
        prewarm: Sequence[TenantSpec] = (),
        brownout_threshold: float | None = 0.75,
        brownout_factor: int = 2,
        store: CheckpointStore | None = None,
        primary: bool | None = None,
        preemption: Union[PreemptionGuard, bool, None] = True,
        on_event: Callable[[str], None] | None = None,
        controller: Any | None = None,
        slos: Any | None = None,
        endpoint: Union[int, bool, None] = None,
        endpoint_host: str = "127.0.0.1",
        fleet_dead_after: float = 5.0,
        compact_records: int | None = None,
        compact_bytes: int | None = None,
        max_replay_seconds: float | None = None,
        **service_kwargs: Any,
    ):
        if brownout_factor < 1:
            raise ValueError(
                f"brownout_factor must be >= 1, got {brownout_factor}"
            )
        if brownout_threshold is not None and not (
            0.0 < brownout_threshold <= 1.0
        ):
            raise ValueError(
                f"brownout_threshold must be in (0, 1], got "
                f"{brownout_threshold}"
            )
        self.root = Path(root)
        if primary is None:
            from ..parallel import is_primary

            primary = is_primary()
        self.primary = bool(primary)
        if store is None:
            store = (
                CheckpointStore()
                if self.primary
                else ReadOnlyCheckpointStore("non-primary daemon process")
            )
        self.store = store
        self.segment_steps = int(segment_steps)
        self.brownout_threshold = (
            None if brownout_threshold is None else float(brownout_threshold)
        )
        self.brownout_factor = int(brownout_factor)
        self.on_event = on_event
        class_list = (
            list(classes)
            if classes is not None
            else [TenantClass("standard", int(max_queue))]
        )
        self.classes: dict[str, TenantClass] = {
            c.name: c for c in class_list
        }
        if len(self.classes) != len(class_list):
            raise ValueError("duplicate TenantClass names")
        self.prewarm_specs = list(prewarm)
        for name, value in (
            ("compact_records", compact_records),
            ("compact_bytes", compact_bytes),
            ("max_replay_seconds", max_replay_seconds),
        ):
            if value is not None and value <= 0:
                raise ValueError(f"{name} must be > 0, got {value}")
        self.compact_records = (
            None if compact_records is None else int(compact_records)
        )
        self.compact_bytes = (
            None if compact_bytes is None else int(compact_bytes)
        )
        self.max_replay_seconds = (
            None if max_replay_seconds is None else float(max_replay_seconds)
        )
        if controller is None and (
            self.compact_records is not None
            or self.compact_bytes is not None
            or self.max_replay_seconds is not None
        ):
            # Compaction decisions must be journaled + replayable like
            # every other control-plane action: arming a threshold
            # without a controller attaches a default one (the router's
            # precedent), inert for every unarmed plane.
            from ..control import Controller

            controller = Controller()
        self.controller = controller
        self.service = OptimizationService(
            self.root,
            lanes_per_pack=lanes_per_pack,
            segment_steps=segment_steps,
            max_queue=max_queue,
            seed=seed,
            preemption=preemption,
            store=store,
            on_event=on_event,
            controller=controller,
            **service_kwargs,
        )
        self._registry: MetricsRegistry | None = (
            self.service.obs.registry if self.service.obs is not None else None
        )
        self.journal = RequestJournal(
            self.root / self.JOURNAL_NAME,
            store=store,
            registry=self._registry,
        )
        if slos is None:
            self.slo: SLOTracker | None = None
        elif isinstance(slos, SLOTracker):
            self.slo = slos
        else:
            self.slo = SLOTracker(list(slos), registry=self._registry)
        if (
            controller is not None
            and self.slo is not None
            and getattr(controller, "slo", None) is None
        ):
            # The formalized objectives become the controller's journaled
            # brown-out / shed evidence (first binder wins).
            controller.slo = self.slo
        self.endpoint: IntrospectionEndpoint | None = None
        if endpoint is not None and endpoint is not False:
            self.endpoint = IntrospectionEndpoint(
                metrics=self._metrics_text,
                healthz=self._healthz,
                statusz=self._statusz,
                flight=self._flight_window,
                instrument=self._registry,
                host=endpoint_host,
                port=0 if endpoint is True else int(endpoint),
            )
        self.fleet_dead_after = float(fleet_dead_after)
        # A PRIVATE fleet registry (not the live process one): this
        # daemon's own series arrive through its own beat; merging them
        # into the process registry would double-count.  Constructed
        # eagerly — endpoint handler threads race a lazy build.
        self._aggregator = FleetAggregator()
        self._fleet_health: Any | None = None
        if controller is not None and controller.journal is None:
            # Decisions ride the daemon's own request journal (advisory
            # appends; the tenant fold skips uid-less records).  A
            # non-primary daemon's read-only store refuses the appends —
            # the controller warns once and keeps deciding in-memory.
            controller.journal = self.journal
        # Controller-driven tenant evictions must be journal-acked like
        # operator evictions (an acked evict parks on restart): route the
        # service's trend-eviction seam through the daemon's durable
        # evict.
        self.service.evict_hook = self.evict
        if exec_cache is True:
            exec_cache = ExecutableCache(
                self.root / self.EXEC_CACHE_DIR,
                store=store,
                on_event=on_event,
                registry=(
                    self.service.obs.registry
                    if self.service.obs is not None
                    else None
                ),
            )
        self.exec_cache: ExecutableCache | None = exec_cache or None
        self.xla_cache_enabled = bool(xla_cache) and (
            enable_xla_compilation_cache(self.root / self.XLA_CACHE_DIR)
        )
        self.stats = DaemonStats()
        self.started = False
        self.brownout = False
        # uids whose terminal "complete" record is already journaled.
        self._journaled_complete: set[int] = set()
        # class of each live tenant, by uid (replayed + submitted).
        self._class_by_uid: dict[int, str] = {}
        self._last_segment_seconds: float | None = None
        # Journaled-but-not-yet-applied steer knobs, by uid: acked by
        # :meth:`steer` (journal append BEFORE the ack, like submits) and
        # materialized onto the tenant record at the next boundary.
        self._pending_steer: dict[int, dict[str, int]] = {}
        # An attached network gateway (evox_tpu.service.Gateway) registers
        # itself here so /statusz grows a "gateway" section (request /
        # error / retry-after counters, per-principal tenant counts).
        self.gateway: Any | None = None
        # An attached ChaosConductor registers itself the same way:
        # /statusz grows a "chaos" section (plan digest, injected-event
        # and violation counts for the live run).
        self.chaos: Any | None = None

    # -- events / metrics ---------------------------------------------------
    def _event(self, msg: str, *, warn: bool = False, **payload: Any) -> None:
        if self.service.obs is not None:
            self.service.obs.event(
                "daemon",
                msg,
                severity="warning" if warn else "info",
                **payload,
            )
        if self.on_event is not None:
            self.on_event(msg)
        elif warn:
            warnings.warn(msg)

    def _gauge(self, name: str, value: float, help: str = "", **labels: Any):
        if self.service.obs is not None:
            self.service.obs.gauge(name, help, **labels).set(value)

    def _inc(self, name: str, help: str = "", **labels: Any) -> None:
        if self.service.obs is not None:
            self.service.obs.counter(name, help, **labels).inc()

    # -- introspection endpoint providers (read-only, fail-safe) -------------
    # Every provider runs on an endpoint handler thread and must never
    # mutate serving state; snapshots are taken as list() copies so a
    # boundary mutating a dict mid-scrape cannot break iteration.
    def _fleet_beats(self) -> dict[int, dict[str, Any]]:
        hb = self.root / "heartbeats"
        if not hb.is_dir():
            return {}
        from ..parallel.multihost import read_heartbeats

        return read_heartbeats(hb)

    def _fleet_report(self, beats: dict[int, dict[str, Any]]) -> Any | None:
        if not beats:
            return None
        from ..parallel.multihost import FleetHealth

        world = max(beats) + 1
        if (
            self._fleet_health is None
            or self._fleet_health.num_processes != world
        ):
            # Every expected host here HAS beaten (the world is derived
            # from observed beats), so the start-grace path is inert.
            self._fleet_health = FleetHealth(
                self.root / "heartbeats",
                world,
                dead_after=self.fleet_dead_after,
            )
        return self._fleet_health.check()

    def _metrics_text(self) -> str:
        beats = self._fleet_beats()
        if beats:
            self._aggregator.update(beats, self._fleet_report(beats))
            return self._aggregator.to_prometheus()
        if self._registry is not None:
            return self._registry.to_prometheus()
        return MetricsRegistry().to_prometheus()  # header-only: obs is off

    def _healthz(self) -> tuple[bool, dict[str, Any]]:
        payload: dict[str, Any] = {
            "started": self.started,
            "brownout": self.brownout,
            "tenants": len(self.service._tenants),
            "queued": len(self.service._queue),
        }
        healthy = True
        beats = self._fleet_beats()
        report = self._fleet_report(beats)
        if report is not None:
            payload.update(report.to_json())
            healthy = report.healthy
        return healthy, payload

    def _statusz(self) -> dict[str, Any]:
        tenants: dict[str, Any] = {}
        counts: dict[str, int] = {}
        for tid, rec in list(self.service._tenants.items()):
            status = rec.status.value
            counts[status] = counts.get(status, 0) + 1
            tenants[tid] = {
                "status": status,
                "uid": rec.uid,
                "lane": rec.lane,
                "generations": rec.generations,
                "n_steps": int(rec.spec.n_steps),
                "class": self._class_by_uid.get(rec.uid, "standard"),
            }
        queue = {
            name: self._class_depth(name) for name in sorted(self.classes)
        }
        out: dict[str, Any] = {
            "schema": OBS_SCHEMA_VERSION,
            "time": time.time(),
            "started": self.started,
            "brownout": self.brownout,
            "segment_steps": self.service.segment_steps,
            "round_seconds": self._last_segment_seconds,
            "queue_depth": queue,
            "queue_budget": {
                name: c.queue_budget for name, c in sorted(self.classes.items())
            },
            "tenants": tenants,
            "tenant_counts": counts,
            "stats": {
                "segments_run": self.service.stats.segments_run,
                "submitted": self.service.stats.submitted,
                "admitted": self.service.stats.admitted,
                "completed": self.service.stats.completed,
                "rejections": len(self.service.stats.rejections),
                "restarts": self.service.stats.restarts,
                "quarantines": self.service.stats.quarantines,
                "sheds": self.stats.sheds,
                "brownout_entries": self.stats.brownout_entries,
                "replayed_tenants": self.stats.replayed_tenants,
                "journal_append_failures": self.stats.journal_append_failures,
                "steers_pending": len(self._pending_steer),
            },
        }
        out["journal"] = self._journal_statusz()
        if self.gateway is not None:
            try:
                out["gateway"] = self.gateway.statusz_payload()
            except Exception as e:  # noqa: BLE001 - read-only, fail-safe
                out["gateway"] = {"error": f"{type(e).__name__}: {e}"}
        if self.chaos is not None:
            try:
                out["chaos"] = self.chaos.statusz_payload()
            except Exception as e:  # noqa: BLE001 - read-only, fail-safe
                out["chaos"] = {"error": f"{type(e).__name__}: {e}"}
        if self.exec_cache is not None:
            cache = self.exec_cache.stats
            hits = int(getattr(cache, "hits", 0))
            misses = int(getattr(cache, "misses", 0))
            out["exec_cache"] = {
                "hits": hits,
                "misses": misses,
                "hit_rate": (
                    hits / (hits + misses) if (hits + misses) else None
                ),
                "quarantines": int(getattr(cache, "quarantines", 0)),
            }
        if self.controller is not None:
            # The decision-journal tail: newest last, manifests only —
            # a READ of the controller's record, never a consult (a
            # scrape must not mint decisions).
            out["decisions"] = [
                d.to_manifest()
                for d in list(self.controller.decisions)[-20:]
            ]
        if self.slo is not None:
            out["slo"] = self.slo.describe()
        return out

    def _journal_statusz(self) -> dict[str, Any]:
        """The journal/recovery strip: growth, snapshot anchoring, last
        measured recovery time, and the compaction decision tail —
        everything ``evoxtop`` renders and the ``--max-snapshot-age``
        probe bounds."""
        snapshot_at = self.journal.snapshot_at
        strip: dict[str, Any] = {
            "bytes": self.journal.size_bytes,
            "records_since_snapshot": self.journal.records_since_snapshot,
            "snapshot_seq": self.journal.snapshot_seq,
            "snapshot_age_seconds": (
                None
                if snapshot_at is None
                else max(0.0, time.time() - snapshot_at)
            ),
            "replay_seconds": self.stats.replay_seconds,
            "compactions": self.stats.compactions,
            "compaction_failures": self.stats.compaction_failures,
            "fallbacks": self.journal.snapshot_fallbacks,
            "armed": self._compaction_armed(),
        }
        if self.controller is not None:
            strip["decisions"] = [
                m
                for m in (
                    d.to_manifest()
                    for d in list(self.controller.decisions)[-40:]
                )
                if m.get("kind") == "compact"
            ][-4:]
        return strip

    def _flight_window(self, tenant_id: str) -> list[dict[str, float]] | None:
        record = self.service._tenants.get(tenant_id)
        if record is not None and record.flight is not None:
            return record.flight.rows()
        obs = self.service.obs
        if (
            record is None
            and obs is not None
            and obs.flight is not None
            and tenant_id == "__service__"
        ):
            return obs.flight.rows()
        return None

    def _slo_admission(self, tenant_class: str, accepted: bool) -> None:
        if self.slo is not None:
            self.slo.record(
                SIGNAL_ADMISSION, accepted, tenant_class=tenant_class
            )
            self.slo.publish()

    # -- journal ------------------------------------------------------------
    def _journal(self, kind: str, *, required: bool, **data: Any) -> bool:
        """Append one lifecycle record.  ``required=True`` (the ack path:
        submits) propagates failure as :class:`JournalError`; advisory
        records (completions — reconstructible from namespaces) warn and
        continue."""
        try:
            self.journal.append(kind, **data)
        except JournalError as e:
            self.stats.journal_append_failures += 1
            if required:
                raise
            self._event(
                f"journal append of advisory {kind!r} record failed ({e}); "
                f"state stays reconstructible from checkpoint namespaces",
                warn=True,
            )
            return False
        self._inc(
            "evox_daemon_journal_records_total",
            "Journal records durably appended, by kind.",
            kind=kind,
        )
        return True

    # -- start / replay ------------------------------------------------------
    # ServiceDaemon.step() is a HOST-side scheduling round (same contract
    # as OptimizationService.step); the linter's name-based step-family
    # scope pulls start/_update_brownout into compiled scope through the
    # call graph, but nothing here is ever traced.
    def start(self) -> int:  # graftlint: disable=GL005
        """Replay the journal (repairing any damaged tail), resubmit every
        live tenant, and pre-warm the declared bucket grid plus every
        replayed bucket.  Returns the number of tenants restored.
        Idempotent."""
        if self.started:
            return 0
        self.started = True
        if self.endpoint is not None and not self.endpoint.started:
            self.endpoint.start()
            self._event(
                f"introspection endpoint serving at {self.endpoint.url} "
                f"(/metrics /healthz /statusz /flightz/<tenant_id>)"
            )
        t_replay = time.perf_counter()
        records, damage = self.journal.replay(quarantine=self.primary)
        for note in self.journal.replay_notes:
            # Snapshot-fallback recovery anomalies: the loudness
            # contract — an operator must see every degraded path taken.
            self._inc(
                "evox_daemon_snapshot_fallbacks_total",
                "Degraded recovery paths taken at replay (snapshot "
                "fallback, restored swap, gap warnings).",
            )
            self._event(f"journal recovery: {note}", warn=True)
        if damage is not None:
            self.stats.journal_damage.append(damage.reason)
            self._inc(
                "evox_daemon_journal_tail_quarantines_total",
                "Damaged journal tails quarantined at replay.",
            )
            self._event(
                f"journal replay: damaged tail at byte {damage.offset} "
                f"({damage.reason}); {damage.bytes_quarantined} bytes "
                + (
                    f"quarantined to {damage.quarantine_path.name}"
                    if damage.quarantine_path is not None
                    else "could not be quarantined"
                )
                + ("; journal repaired" if damage.truncated else ""),
                warn=True,
            )
        self.stats.replayed_records = len(records)
        base = self.journal.snapshot_state
        if base is not None:
            self._event(
                f"journal replay anchored at snapshot seq "
                f"{self.journal.snapshot_seq} "
                f"({len(records)} suffix records to fold)"
            )
        # Fold the snapshot base + record suffix into per-uid final
        # lifecycle state (at-least-once: duplicates collapse, last
        # state wins) — the same pure fold compaction snapshots through,
        # so both cold-start paths compute identical state.
        state, anomalies = fold_daemon_records(records, base=base)
        for msg in anomalies:
            self._event(f"journal replay: {msg}", warn=True)
        live: dict[int, dict[str, Any]] = {
            int(u): d for u, d in state["live"].items()
        }
        parked: set[int] = {int(u) for u in state["parked"]}
        steers: dict[int, dict[str, int]] = {
            int(u): dict(k) for u, k in state["steers"].items()
        }
        self._journaled_complete.update(int(u) for u in state["completed"])
        restored = 0
        if live:
            # Replay must never bounce off the queue bound the journal
            # itself admitted through.
            original_bound = self.service.max_queue
            self.service.max_queue = max(original_bound, len(live))
            try:
                for uid in sorted(live):
                    data = live[uid]
                    try:
                        spec = _decode_spec(data["spec"])
                    except Exception as e:  # noqa: BLE001 - evidence > crash
                        self._event(
                            f"journal replay: tenant uid {uid} "
                            f"({data.get('tenant_id')!r}) has an "
                            f"undecodable spec ({type(e).__name__}: {e}); "
                            f"skipped — its namespace remains on disk",
                            warn=True,
                        )
                        continue
                    # Pin the journaled uid; every other field (workload,
                    # growth ladder, solution transform, budget) replays
                    # exactly as submitted.
                    spec = dataclass_replace(spec, uid=uid)
                    # Acked steers materialize BEFORE resubmission: a
                    # budget raised past a journaled completion must
                    # resume the tenant instead of re-materializing the
                    # stale final result.  (Live semantics are "at the
                    # next boundary"; for a steer acked but unapplied at
                    # the kill, resubmission IS the next boundary.)
                    knobs = steers.get(uid, {})
                    if "n_steps" in knobs:
                        spec = dataclass_replace(
                            spec, n_steps=knobs["n_steps"]
                        )
                    try:
                        record = self.service.submit(spec)
                    except AdmissionError as e:
                        self._event(
                            f"journal replay: resubmission of "
                            f"{spec.tenant_id!r} refused ({e.reason}); "
                            f"skipped",
                            warn=True,
                        )
                        continue
                    self._class_by_uid[uid] = data.get("class", "standard")
                    record.steer.update(
                        {
                            k: v
                            for k, v in knobs.items()
                            if k in ("checkpoint_every", "max_restarts")
                        }
                    )
                    restored += 1
                    if uid in parked:
                        # Operator-evicted: journaled intent is "off the
                        # lane until readmitted" — withdraw from the queue
                        # but keep the record (status EVICTED, resumable).
                        self.service.withdraw(
                            spec.tenant_id, to_status=TenantStatus.EVICTED
                        )
            finally:
                self.service.max_queue = original_bound
        self.stats.replayed_tenants = restored
        # The recovery-time SLO signal: replay + fold + resubmission
        # (pre-warm is excluded — compile cost is the exec cache's
        # budget, not the journal's).
        self.stats.replay_seconds = time.perf_counter() - t_replay
        self._gauge(
            "evox_recovery_replay_seconds",
            self.stats.replay_seconds,
            "Wall seconds of the last cold-start recovery (journal "
            "replay + fold + tenant resubmission).",
        )
        if self.slo is not None:
            self.slo.observe(SIGNAL_RECOVERY, self.stats.replay_seconds)
            self.slo.publish()
        self._journal_gauges()
        if restored:
            self._inc(
                "evox_daemon_replayed_tenants_total",
                "Tenants restored from the journal at start.",
            )
            self._event(
                f"replayed {len(records)} journal records; restored "
                f"{restored} tenants "
                f"({self.stats.replay_seconds:.3f}s recovery)"
            )
        # Pre-warm: the declared grid, then every bucket the replay
        # queued (restored tenants must not pay a compile either).
        for spec in self.prewarm_specs:
            self._prewarm_bucket(spec)
        for tenant_id in list(self.service._queue):
            self._prewarm_bucket(self.service.tenant(tenant_id).spec)
        return restored

    def _prewarm_bucket(self, spec: TenantSpec) -> None:
        """AOT-warm (or cache-load) one bucket's programs for both the
        normal and brown-out cadences."""
        bucket = self.service._bucket_for(spec)
        label = _bucket_label(bucket.key)
        lengths = {self.segment_steps}
        if self._brownout_enter() is not None and self.brownout_factor > 1:
            lengths.add(self.segment_steps * self.brownout_factor)
        if all(n in bucket.pack._aot_segment for n in lengths) and (
            bucket.pack._aot_init is not None
        ):
            return
        example = self.service._fresh_state(
            bucket, TenantRecord(spec=spec, uid=0)
        )
        t0 = time.perf_counter()
        results = bucket.pack.prewarm(
            example,
            sorted(lengths),
            cache=self.exec_cache,
            label=label,
        )
        self.stats.prewarmed.update(results)
        hits = sum(results.values())
        if hits:
            self._inc(
                "evox_daemon_prewarm_programs_total",
                "Programs pre-warmed, by source.",
                source="cache",
            )
        self._event(
            f"pre-warmed bucket {label}: {hits}/{len(results)} programs "
            f"from cache ({time.perf_counter() - t0:.2f}s)"
        )

    # -- admission ----------------------------------------------------------
    def submit(
        self,
        spec: TenantSpec,
        *,
        tenant_class: str = "standard",
        journal_extra: dict[str, Any] | None = None,
    ) -> "TenantRecord":
        """Admit one tenant durably: SLO admission control, then the
        service's queue, then the journal — the record is fsync'd before
        this returns (the ack).  Raises :class:`AdmissionError` with a
        structured reason (and ``retry_after_segments`` /
        measured-cadence ``retry_after_seconds`` hints for overload
        sheds) when refused.

        ``journal_extra`` rides extra fields on the journaled submit
        record (the gateway's idempotency key and principal — replay
        rebuilds its exactly-once dedup map from them); keys must not
        collide with the record's own fields."""
        self.start()
        cls = self.classes.get(tenant_class)
        if cls is None:
            self.service._reject(
                spec,
                "unknown-class",
                f"tenant class {tenant_class!r} is not declared "
                f"(have {sorted(self.classes)})",
            )
        existing = self.service._tenants.get(spec.tenant_id)
        readmission = existing is not None and existing.status in (
            TenantStatus.EVICTED,
            TenantStatus.QUARANTINED,
        )
        if existing is not None and not readmission:
            # A duplicate of a QUEUED/RUNNING/COMPLETED id is a
            # non-retryable collision — it must NOT be masked by a
            # retryable "shed" (a client honoring the retry hint would
            # wait and re-collide forever); let the service's own
            # validation reject it with the truthful reason.
            self.service.submit(spec)
            raise AssertionError("collision must have been rejected")
        if cls.sheddable:
            budget = self._effective_budget(cls)
            if self._class_depth(cls.name) >= budget:
                self._shed(spec, cls, budget)
        record = self.service.submit(spec)
        try:
            self._journal(
                "submit",
                required=True,
                tenant_id=spec.tenant_id,
                uid=record.uid,
                n_steps=int(spec.n_steps),
                **{"class": cls.name},
                spec=_encode_spec(spec),
                **(journal_extra or {}),
            )
        except JournalError as e:
            # Un-admit: an un-journaled tenant must not run (a crash
            # would silently lose it after the caller's ack).  A failed
            # READMISSION parks the pre-existing record instead of
            # dropping it — its journaled history (and namespace) must
            # keep describing a real tenant.
            self.service.withdraw(
                spec.tenant_id,
                to_status=TenantStatus.EVICTED if readmission else None,
            )
            self._slo_admission(cls.name, False)
            self.service._reject(
                spec,
                "journal-failed",
                f"the admission record could not be made durable ({e})",
                retry_after_segments=1,
                retry_after_seconds=retry_after_seconds(
                    1, self._last_segment_seconds
                ),
            )
        self._journaled_complete.discard(record.uid)
        self._class_by_uid[record.uid] = cls.name
        # A (re)submit supersedes earlier steering: the fresh spec carries
        # the caller's current intent (mirrors the replay fold).
        self._pending_steer.pop(record.uid, None)
        record.steer.clear()
        self._slo_admission(cls.name, True)
        self._gauge(
            "evox_daemon_queue_depth",
            self._class_depth(cls.name),
            "Queued tenants per admission class.",
            **{"class": cls.name},
        )
        self._prewarm_bucket(spec)
        return record

    def _class_depth(self, name: str) -> int:
        """Queued tenants of one class (unregistered uids — pre-daemon
        journal rows — count as ``standard``).  Snapshot-safe: also
        called from endpoint handler threads mid-boundary, so the queue
        is copied and a tenant withdrawn between the copy and the lookup
        is simply skipped."""
        count = 0
        for tid in list(self.service._queue):
            record = self.service._tenants.get(tid)
            if record is None:
                continue
            if self._class_by_uid.get(record.uid, "standard") == name:
                count += 1
        return count

    def _retry_after(self, cls: TenantClass) -> int:
        """Segments until a retry plausibly lands: the nearest running
        completion, plus how many whole-pack drains the class's queue
        depth represents (fed by the live scheduler state the
        ``evox_service_*`` gauges export)."""
        base = self.service.retry_hint_segments()
        ahead = self._class_depth(cls.name)
        lanes = max(1, self.service.lanes_per_pack)
        return base + ahead // lanes

    def _effective_budget(self, cls: TenantClass) -> int:
        """The class's live queue budget: the configured bound,
        tightened by the controller's SLO-aware shed threshold when one
        is armed (``slo_wait_seconds`` on the controller, fed by the
        measured segment cadence).  A changed effective budget is one
        journaled ``shed-threshold`` decision."""
        if self.controller is None or (
            self.controller.slo_wait_seconds is None
            and getattr(self.controller, "slo", None) is None
        ):
            return cls.queue_budget
        return self.controller.shed_threshold(
            queue_budget=cls.queue_budget,
            segment_seconds=self._last_segment_seconds,
            lanes=self.service.lanes_per_pack,
            tenant_class=cls.name,
            generation=self.service.stats.segments_run,
        )

    def _shed(
        self, spec: TenantSpec, cls: TenantClass, budget: int | None = None
    ) -> None:
        budget = cls.queue_budget if budget is None else budget
        hint = self._retry_after(cls)
        wall = retry_after_seconds(hint, self._last_segment_seconds)
        self.stats.sheds += 1
        self._slo_admission(cls.name, False)
        self._inc(
            "evox_daemon_sheds_total",
            "Submissions shed at a class budget, by class.",
            **{"class": cls.name},
        )
        seconds = (
            f" (~{wall:.1f}s at the current segment cadence)"
            if wall is not None
            else ""
        )
        tightened = (
            f" (tightened from {cls.queue_budget} by the controller's "
            f"SLO target)"
            if budget != cls.queue_budget
            else ""
        )
        self.service._reject(
            spec,
            "shed",
            f"class {cls.name!r} is at its queue budget "
            f"({budget}{tightened}); retry after ~{hint} segment "
            f"boundaries{seconds}",
            retry_after_segments=hint,
            retry_after_seconds=wall,
        )

    # -- brown-out ----------------------------------------------------------
    def _queue_pressure(self) -> float:
        bound = max(1, self.service.max_queue)
        return len(self.service._queue) / bound

    def _brownout_enter(self) -> float | None:
        """The live brown-out entry pressure: the controller's
        ``brownout_enter`` override when set — an armed controller plane
        must not be silently dead just because the daemon's own
        threshold is ``None`` — else the daemon's configured
        ``brownout_threshold``."""
        if (
            self.controller is not None
            and self.controller.brownout_enter is not None
        ):
            return self.controller.brownout_enter
        return self.brownout_threshold

    # Host-side boundary work (see the step-family scope note on start).
    def _update_brownout(self) -> None:  # graftlint: disable=GL005
        enter = self._brownout_enter()
        if enter is None or self.brownout_factor == 1:
            return
        pressure = self._queue_pressure()
        if self.controller is not None:
            # Controller hysteresis: the transition is a journaled
            # decision (enter/exit thresholds in the evidence), the
            # cadence change below is the act half.  Exception-guarded
            # inside the controller — a failure decides "hold" and the
            # cadence stays where it is.
            action = self.controller.brownout(
                pressure=pressure,
                active=self.brownout,
                enter=self.brownout_threshold,
                generation=self.service.stats.segments_run,
            )
            transition = (action == "enter", action == "exit")
        else:
            transition = (
                not self.brownout and pressure >= enter,
                self.brownout and pressure <= enter / 2,
            )
        if transition[0]:
            self.brownout = True
            self.stats.brownout_entries += 1
            self.service.segment_steps = (
                self.segment_steps * self.brownout_factor
            )
            self._inc(
                "evox_daemon_brownout_entries_total",
                "Times the daemon stretched segment cadence under load.",
            )
            self._event(
                f"brown-out: queue pressure {pressure:.2f} >= {enter}; "
                f"segment cadence stretched "
                f"{self.segment_steps} -> {self.service.segment_steps} "
                f"(pre-warmed — no compile)",
                warn=True,
            )
        elif transition[1]:
            self.brownout = False
            self.stats.brownout_exits += 1
            self.service.segment_steps = self.segment_steps
            self._event(
                f"brown-out over: queue pressure {pressure:.2f}; segment "
                f"cadence restored to {self.segment_steps}"
            )
        self._gauge(
            "evox_daemon_brownout",
            1.0 if self.brownout else 0.0,
            "Whether the daemon is in brown-out (stretched cadence).",
        )

    # -- lifecycle ----------------------------------------------------------
    def step(self) -> bool:  # graftlint: disable=GL005
        """One supervised scheduling round: acked steers materialized,
        brown-out check, one service round, then journal the round's
        completions.  :class:`Preempted` is journaled before it
        propagates."""
        self.start()
        self._apply_steers()
        self._update_brownout()
        t0 = time.perf_counter()
        try:
            progressed = self.service.step()
        except Preempted:
            self._journal("preempt", required=False)
            raise
        if progressed:
            self._last_segment_seconds = time.perf_counter() - t0
            self._gauge(
                "evox_daemon_round_seconds",
                self._last_segment_seconds,
                "Wall seconds of the last scheduling round.",
            )
            self._observe_slos(self._last_segment_seconds)
        self._journal_completions()
        self._maybe_compact()
        return progressed

    # -- compaction ---------------------------------------------------------
    def _journal_gauges(self) -> None:
        """Publish the journal-growth gauges the compaction SLO watches."""
        self._gauge(
            "evox_journal_bytes",
            self.journal.size_bytes,
            "Journal file size in bytes.",
        )
        self._gauge(
            "evox_journal_records",
            self.journal.records_since_snapshot,
            "Journal records since the last snapshot anchor (the whole "
            "history when never compacted) — cold-start replay folds "
            "exactly this many.",
        )
        if self.journal.snapshot_at is not None:
            self._gauge(
                "evox_journal_snapshot_age_seconds",
                max(0.0, time.time() - self.journal.snapshot_at),
                "Seconds since the journal's last snapshot was taken.",
            )

    def _compaction_armed(self) -> bool:
        return (
            self.compact_records is not None
            or self.compact_bytes is not None
            or self.max_replay_seconds is not None
        )

    def _maybe_compact(self) -> None:  # graftlint: disable=GL005
        """Boundary-time journal compaction: journal-growth evidence →
        the pure journaled ``compact`` decider (quiet-windowed,
        replayable bit-for-bit) → the crash-safe snapshot/swap protocol.
        Never raises — a refused or failed compaction warns and serving
        continues on the (always-correct) uncompacted journal."""
        self._journal_gauges()
        if (
            not self.primary
            or self.controller is None
            or not self._compaction_armed()
        ):
            return
        evidence = {
            "journal_bytes": self.journal.size_bytes,
            "journal_records": self.journal.records_since_snapshot,
            "live_tenants": len(self.service._tenants),
            "replay_seconds": self.stats.replay_seconds,
            "compact_records": self.compact_records,
            "compact_bytes": self.compact_bytes,
            "max_replay_seconds": self.max_replay_seconds,
        }
        action = self.controller.compact(
            evidence=evidence, generation=self.service.stats.segments_run
        )
        if action == "compact":
            self._compact_journal()

    def _compact_journal(self) -> None:
        """One crash-safe compaction through the journal's protocol,
        folding with the same pure fold replay uses."""

        def fold(
            base: dict[str, Any] | None, records: list[Any]
        ) -> dict[str, Any]:
            state, _anomalies = fold_daemon_records(records, base=base)
            return state

        t0 = time.perf_counter()
        try:
            result = self.journal.compact(fold)
        except JournalError as e:
            self.stats.compaction_failures += 1
            self._inc(
                "evox_daemon_compaction_failures_total",
                "Journal compactions that failed (serving continued on "
                "the uncompacted journal).",
            )
            self._event(f"journal compaction failed ({e})", warn=True)
            return
        self.stats.compactions += 1
        self._inc(
            "evox_daemon_compactions_total",
            "Successful journal compactions.",
        )
        self._journal_gauges()
        self._event(
            f"journal compacted at seq {result.seq}: "
            f"{result.folded_records} records ({result.bytes_before} "
            f"bytes) folded into {result.snapshot_path.name}; journal "
            f"now {result.bytes_after} bytes"
            + (
                f"; GC'd {len(result.removed)} superseded artifacts"
                if result.removed
                else ""
            )
            + f" ({time.perf_counter() - t0:.3f}s)"
        )

    def _observe_slos(self, round_seconds: float) -> None:
        """Score one scheduling round against the declared objectives:
        round wall seconds against every class's latency SLO, and the
        realized per-tenant generation rate against each running
        tenant's class throughput floor."""
        if self.slo is None:
            return
        for name in self.classes:
            self.slo.observe(
                SIGNAL_SEGMENT_SECONDS, round_seconds, tenant_class=name
            )
        if round_seconds > 0:
            gens_per_sec = self.service.segment_steps / round_seconds
            running: dict[str, int] = {}
            for rec in list(self.service._tenants.values()):
                if rec.status is TenantStatus.RUNNING:
                    cls = self._class_by_uid.get(rec.uid, "standard")
                    running[cls] = running.get(cls, 0) + 1
            for cls, n in running.items():
                self.slo.observe(
                    SIGNAL_TENANT_GENS,
                    gens_per_sec,
                    tenant_class=cls,
                    n=n,
                )
        self.slo.publish()

    def _journal_completions(self) -> None:
        for record in self.service._tenants.values():
            if (
                record.status is TenantStatus.COMPLETED
                and record.uid not in self._journaled_complete
            ):
                if self._journal(
                    "complete",
                    required=False,
                    tenant_id=record.spec.tenant_id,
                    uid=record.uid,
                    generations=record.generations,
                ):
                    self._journaled_complete.add(record.uid)

    def run(self, max_rounds: int | None = None) -> None:
        """Drain the service under the daemon's lifecycle (preemption
        guard installed for the duration, rounds journaled).  Mirrors
        :meth:`OptimizationService.run` semantics."""
        self.start()
        guard = self.service.preemption
        installed = False
        if guard is not None:
            if self.service._owns_guard:
                guard.reset()
            if not guard.installed:
                guard.install()
                installed = True
        try:
            rounds = 0
            while True:
                if max_rounds is not None and rounds >= max_rounds:
                    return
                progressed = self.step()
                rounds += 1
                if not progressed:
                    return
        finally:
            if installed:
                guard.uninstall()
            self.journal.close()

    def steer(
        self,
        tenant_id: str,
        *,
        n_steps: int | None = None,
        checkpoint_every: int | None = None,
        max_restarts: int | None = None,
        journal_extra: dict[str, Any] | None = None,
    ) -> dict[str, int]:
        """Adjust one live tenant's scheduling knobs **durably**: the
        generation budget (``n_steps`` — raise to extend a promising run,
        lower to wind one down at the next boundary), the checkpoint
        cadence, and the per-tenant restart budget.  The ``steer`` record
        is journaled BEFORE this returns (the ack — same crash-safety
        contract as submits), and the knobs materialize at the **next
        segment boundary**; a daemon killed between the ack and the
        boundary replays the steer at restart, so an acked steer is never
        lost.  Values only — steering never touches lane state, which is
        why a steered, killed, and restarted run stays bit-identical to a
        steered uninterrupted one.

        Knobs are validated before the journal write (a doomed call
        leaves no record): ``n_steps >= 1``, ``checkpoint_every >= 1``,
        ``max_restarts >= 0``, at least one knob set.  Raises
        ``KeyError`` for unknown tenants (a steer can only follow the
        submit that admitted its tenant — the journal replay enforces
        the same ordering) and ``RuntimeError`` for COMPLETED ones.
        Returns the accepted knob dict.  A later (re)submit of the same
        tenant supersedes pending steering."""
        self.start()
        record = self.service.tenant(tenant_id)
        knobs: dict[str, int] = {}
        for name, value, floor in (
            ("n_steps", n_steps, 1),
            ("checkpoint_every", checkpoint_every, 1),
            ("max_restarts", max_restarts, 0),
        ):
            if value is None:
                continue
            value = int(value)
            if value < floor:
                raise ValueError(
                    f"steer {name} must be >= {floor}, got {value}"
                )
            knobs[name] = value
        if not knobs:
            raise ValueError(
                f"steer of {tenant_id!r} adjusts nothing (set at least "
                f"one of {', '.join(STEER_KNOBS)})"
            )
        if record.status is TenantStatus.COMPLETED:
            raise RuntimeError(
                f"tenant {tenant_id!r} is completed; resubmit it (with a "
                f"refreshed budget) instead of steering"
            )
        self._journal(
            "steer",
            required=True,
            tenant_id=tenant_id,
            uid=record.uid,
            **knobs,
            **(journal_extra or {}),
        )
        self._pending_steer.setdefault(record.uid, {}).update(knobs)
        self._event(
            f"steer acked for tenant {tenant_id!r} (uid {record.uid}): "
            + ", ".join(f"{k}={v}" for k, v in sorted(knobs.items()))
            + " — applies at the next segment boundary"
        )
        return knobs

    def _apply_steers(self) -> None:
        """Materialize acked steer knobs onto their tenant records — the
        boundary half of :meth:`steer` (runs at the top of every
        :meth:`step`, before the service round, so admission and verdict
        logic in the round already sees the steered values)."""
        if not self._pending_steer:
            return
        for uid, knobs in list(self._pending_steer.items()):
            record = self.service._tenants_by_uid.get(uid)
            del self._pending_steer[uid]
            if record is None:  # retired between ack and boundary
                continue
            if "n_steps" in knobs:
                record.spec = dataclass_replace(
                    record.spec, n_steps=knobs["n_steps"]
                )
                # A raised budget re-arms a completion record exactly
                # like the readmission path would.
                if knobs["n_steps"] > record.generations:
                    self._journaled_complete.discard(uid)
            record.steer.update(
                {
                    k: v
                    for k, v in knobs.items()
                    if k in ("checkpoint_every", "max_restarts")
                }
            )
            self._inc(
                "evox_daemon_steers_applied_total",
                "Journaled steer records materialized at a boundary.",
            )
            self._event(
                f"steer applied to tenant {record.spec.tenant_id!r}: "
                + ", ".join(f"{k}={v}" for k, v in sorted(knobs.items()))
            )

    def park(self, tenant_id: str) -> str:
        """Withdraw a tenant from service durably, whatever its phase:
        a RUNNING tenant is evicted (checkpoint + lane freed — exactly
        :meth:`evict`), a QUEUED one is withdrawn from the admission
        queue to the same parked EVICTED status; both journal the same
        ``evict`` record BEFORE mutating, so restart replay parks the
        tenant either way.  The gateway's ``DELETE`` maps here.  Returns
        the resulting status string; raises ``KeyError`` for unknown
        tenants and ``RuntimeError`` for tenants already off a lane
        (COMPLETED/EVICTED/QUARANTINED — nothing to withdraw)."""
        self.start()
        record = self.service.tenant(tenant_id)
        if record.status is TenantStatus.RUNNING:
            self.evict(tenant_id)
            return record.status.value
        if record.status is not TenantStatus.QUEUED:
            raise RuntimeError(
                f"tenant {tenant_id!r} is {record.status.value} and holds "
                f"no lane or queue slot; forget it to retire the record"
            )
        self._journal(
            "evict", required=True, tenant_id=tenant_id, uid=record.uid
        )
        self.service.withdraw(tenant_id, to_status=TenantStatus.EVICTED)
        return record.status.value

    def evict(self, tenant_id: str) -> None:
        """Checkpoint + free a tenant's lane, durably.  The record is
        journaled BEFORE the service mutates (``required=True``) — an
        acked eviction must park on restart, never silently resume; a
        crash between the record and the lane surgery merely parks the
        tenant at its last boundary checkpoint (at-least-once)."""
        self.start()
        record = self.service.tenant(tenant_id)
        if record.lane is None:
            # Same precondition service.evict enforces — validated before
            # the journal write so a doomed call leaves no record.
            raise RuntimeError(
                f"tenant {tenant_id!r} is {record.status.value} and holds "
                f"no lane"
            )
        self._journal(
            "evict", required=True, tenant_id=tenant_id, uid=record.uid
        )
        self.service.evict(tenant_id)

    def forget(self, tenant_id: str) -> None:
        """Retire a tenant's record durably AND reclaim its disk: the
        ``retire`` record is journaled BEFORE anything mutates (an acked
        retirement must not resurrect on restart), and only once that
        successor is durable does the service GC the tenant's checkpoint
        namespace and flight dir (the durable-successor rule — a crash
        between the record and the GC leaves orphan files a later forget
        or restart re-reaps, never a journaled tenant without its
        data)."""
        self.start()
        record = self.service._tenants.get(tenant_id)
        if record is None:
            return
        if record.status in (TenantStatus.QUEUED, TenantStatus.RUNNING):
            # Same precondition service.forget enforces — validated before
            # the journal write so a doomed call leaves no record.
            raise RuntimeError(
                f"tenant {tenant_id!r} is {record.status.value}; evict it "
                f"before forgetting"
            )
        self._journal(
            "retire", required=True, tenant_id=tenant_id, uid=record.uid
        )
        self.service.forget(tenant_id, purge=self.primary)
        self._journaled_complete.discard(record.uid)
        self._class_by_uid.pop(record.uid, None)

    def result(self, tenant_id: str):
        return self.service.result(tenant_id)

    def tenant(self, tenant_id: str) -> "TenantRecord":
        return self.service.tenant(tenant_id)

    def close(self) -> None:
        if self.endpoint is not None:
            self.endpoint.stop()
        self.journal.close()

    # -- fleet --------------------------------------------------------------
    def fleet_supervisor(
        self,
        command: Callable[..., Sequence[str]],
        num_processes: int,
        **kwargs: Any,
    ):
        """A :class:`~evox_tpu.resilience.FleetSupervisor` over daemon
        workers sharing this root.  ``command`` maps a ``WorkerSpec`` to
        the argv of one daemon process (the worker constructs a
        ``ServiceDaemon`` over the same root and calls :meth:`run`).

        Host loss becomes tenant migration for free: the relaunched
        worker replays the shared journal, resumes every tenant's
        namespace checkpoints, and loads the shared executable cache —
        the surviving fleet carries every tenant forward with zero lost
        acknowledged work and zero cold-start compiles."""
        from ..resilience.fleet import FleetSupervisor

        kwargs.setdefault("heartbeat_dir", self.root / "heartbeats")
        return FleetSupervisor(
            command,
            num_processes,
            checkpoint_dir=self.root,
            **kwargs,
        )
