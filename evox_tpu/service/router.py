"""Cross-host tenant scheduler: capacity-aware, journaled, crash-safe.

PR 16's :class:`~evox_tpu.service.Gateway` made the network write path
exactly-once, but scheduling stayed one daemon, one host.  This module
makes **placement itself a first-class, replayable decision**: a
:class:`TenantRouter` fronts N per-host
:class:`~evox_tpu.service.ServiceMember`\\ s and owns the authoritative
tenant → member map, built to the same survive-anything standard as the
journal planes underneath it:

* **Capacity-aware bucket affinity.**  Members advertise capacity (free
  lanes per compilation bucket, per-class queue depths, measured segment
  cadence, exec-cache warmth) through the existing
  :class:`~evox_tpu.parallel.HostHeartbeat` payload; the router places
  each submit on the member already running that ``bucket_key`` with a
  free lane — packs stay dense and a warm executable cache is reused —
  falling back to the least-loaded live member.
* **Journal-before-ack placement.**  Every placement is appended to the
  router's own :class:`~evox_tpu.service.RequestJournal` as a
  ``kind="placement"`` record (tenant, pinned ``uid``, member, class,
  bucket, encoded spec, and the client's forwarded ``Idempotency-Key``)
  **before** the forward and the ack, so gateway exactly-once semantics
  hold end-to-end through the extra hop: a router SIGKILL+restart
  rebuilds the placement map — and the gateway its dedup map — from one
  read-only replay (the PR-16 ``Gateway.start()`` idiom), then
  reconciles any journaled-but-unforwarded placement against the
  member's own journal.
* **Survivor migration.**  The router consumes
  :class:`~evox_tpu.parallel.FleetHealth` dead/wedged/slow verdicts
  each round: a dead member's tenants are migrated onto survivors by
  copying their per-tenant checkpoint namespaces and resubmitting with
  the pinned ``uid`` (identity-keyed PRNG — the PR-7/PR-11 resume
  contract, now cross-daemon), every move journaled as a
  ``kind="migration"`` record.  Resumed state is bit-identical to an
  uninterrupted run; wedged/slow members keep their tenants but take no
  new placements.
* **Chaos degrades, never wedges.**  Forwards cross a transport-shaped
  member link (``router.links[i]`` — wrap it in
  :class:`~evox_tpu.resilience.FaultyTransport` to inject drops, torn
  replies, delays, duplicates); a failed forward becomes a structured
  :class:`~evox_tpu.service.AdmissionError` the gateway maps to
  503 + ``Retry-After``, and a duplicated or reply-dropped forward is
  reconciled by ``uid`` so admission stays exactly-once.
* **Controller-driven autoscale.**  A pure, journaled
  :func:`~evox_tpu.control.decide_autoscale` decider (replayable
  bit-for-bit like every ``control/`` decision) drains-then-retires
  idle members and requests growth under sustained shed pressure or SLO
  burn; ``spawn_member=`` turns grow decisions into live members.

The router exposes the daemon surface the gateway fronts (``submit`` /
``steer`` / ``park`` / ``step`` / ``journal`` / ``service`` view /
introspection providers), so ``Gateway(TenantRouter(...), tokens=...)``
serves a whole fleet through one authenticated front door.
"""

from __future__ import annotations

import json
import shutil
import time
from dataclasses import replace as dataclass_replace
from pathlib import Path
from typing import Any, Callable, Iterator, Mapping, Sequence, Union

from ..obs.aggregate import FleetAggregator
from ..obs.endpoint import IntrospectionEndpoint
from ..obs.metrics import MetricsRegistry
from ..obs.version import OBS_SCHEMA_VERSION
from .daemon import STEER_KNOBS, _bucket_label, _encode_spec
from .journal import JournalError, RequestJournal
from .member import MEMBER_API_PREFIX, ServiceMember
from .service import AdmissionError, retry_after_seconds
from .tenant import TenantSpec, bucket_key

__all__ = ["TenantRouter", "fold_router_records"]

#: How many migration / autoscale events the statusz tail keeps.
_EVENT_TAIL = 50


def fold_router_records(
    records: Sequence[Any], base: dict[str, Any] | None = None
) -> tuple[dict[str, Any], list[str]]:
    """Pure fold of a router journal record stream onto an optional
    snapshot base state; returns ``(state, anomalies)``.

    The same function is both replay's fold (:meth:`TenantRouter.start`
    seeds from ``journal.snapshot_state`` and folds the suffix) and
    compaction's (:meth:`~evox_tpu.service.RequestJournal.compact` folds
    the whole history into the next snapshot), so a snapshot-anchored
    cold start computes exactly the placement map a full replay would.

    ``state`` is canonical-JSON-serializable: ``placements`` maps
    tenant_id → the folded placement record (uid, member, class, bucket,
    encoded spec, ``auto`` for migration-minted moves — ``confirmed`` is
    runtime-only and always False on restore), plus sorted
    ``drained`` / ``retired`` member-index lists and the next free
    ``uid_next``.  At-least-once semantics are the journal's: duplicates
    collapse, last placement wins."""
    base = base or {}
    placements: dict[str, dict[str, Any]] = {
        str(t): dict(p) for t, p in (base.get("placements") or {}).items()
    }
    drained = {int(i) for i in base.get("drained") or []}
    retired = {int(i) for i in base.get("retired") or []}
    uid_next = int(base.get("uid_next") or 0)
    idem: dict[str, dict[str, Any]] = {
        str(k): dict(v) for k, v in (base.get("idem") or {}).items()
    }
    anomalies: list[str] = []
    for rec in records:
        data = rec.data
        key = data.get("idem")
        principal = data.get("principal")
        if key and principal:
            # Mirrors Gateway._rebuild_idem exactly — the snapshot must
            # preserve the dedup map a full-journal replay would build.
            idem[f"{principal}:{key}"] = {
                "route": rec.kind,
                "tenant_id": data.get("tenant_id"),
                "uid": data.get("uid"),
                "knobs": {
                    k: data[k]
                    for k in STEER_KNOBS
                    if rec.kind == "steer" and k in data
                },
            }
        if rec.kind in ("placement", "migration"):
            tid = str(data.get("tenant_id"))
            placements[tid] = {
                "tenant_id": tid,
                "uid": int(data.get("uid", 0)),
                "member": int(data.get("member", 0)),
                "class": str(data.get("class", "standard")),
                "bucket": str(data.get("bucket", "")),
                "spec": str(data.get("spec", "")),
                "auto": rec.kind == "migration",
            }
            if rec.kind == "migration":
                # Keep the move's provenance so the statusz migration
                # tail survives compaction.
                placements[tid]["from"] = data.get("from")
                if data.get("reason"):
                    placements[tid]["reason"] = str(data["reason"])
            uid_next = max(uid_next, int(data.get("uid", 0)) + 1)
        elif rec.kind == "drain-member":
            drained.add(int(data.get("member", -1)))
        elif rec.kind == "retire-member":
            index = int(data.get("member", -1))
            retired.add(index)
            drained.discard(index)
    return (
        {
            "placements": placements,
            "drained": sorted(drained),
            "retired": sorted(retired),
            "uid_next": uid_next,
            "idem": idem,
        },
        anomalies,
    )


class _FleetTenants(Mapping):
    """Read-only tenant view across the fleet, resolved through the
    placement map (the owning member's record wins — a migrated tenant
    may transiently exist on two roots)."""

    def __init__(self, router: "TenantRouter"):
        self._router = router

    def get(self, tenant_id: Any, default: Any = None) -> Any:
        record = self._router._tenant_record(tenant_id)
        return record if record is not None else default

    def __getitem__(self, tenant_id: Any) -> Any:
        record = self._router._tenant_record(tenant_id)
        if record is None:
            raise KeyError(tenant_id)
        return record

    def __iter__(self) -> Iterator[str]:
        return iter(list(self._router._placements))

    def __len__(self) -> int:
        return len(self._router._placements)


class _FleetService:
    """The slice of the :class:`OptimizationService` surface the gateway
    touches (``_tenants`` lookups and checkpoint ``namespace``),
    answered fleet-wide through the placement map."""

    def __init__(self, router: "TenantRouter"):
        self._router = router
        self._tenants = _FleetTenants(router)

    def namespace(self, tenant_id: str) -> Path:
        member = self._router._owner(tenant_id)
        if member is None:
            raise KeyError(f"unknown tenant {tenant_id!r}")
        return member.daemon.service.namespace(tenant_id)


class TenantRouter:
    """Capacity-aware scheduler fronting N per-host daemon members.

    Usage::

        members = [ServiceMember(i, root / f"members/{i}",
                                 heartbeat_dir=root / "heartbeats",
                                 lanes_per_pack=8, segment_steps=16,
                                 seed=0)
                   for i in range(2)]
        router = TenantRouter(root, members)
        router.start()            # replay placements, reconcile members
        router.submit(TenantSpec("alice-1", PSO(...), Ackley(),
                                 n_steps=400))
        while router.step():      # rounds + health checks + autoscale
            pass
        # SIGKILL at ANY point, then in a fresh process: same
        # constructor over the same roots; start() replays the journal
        # to the same placement map and dedups retried submits.

    :param root: router directory — the placement journal
        (``router_journal.jsonl``) and the shared fleet heartbeat
        directory (``heartbeats/``) live under it.  Member roots are
        the members' own.
    :param members: the fleet.  Indexes must be unique and roots
        distinct; ``seed`` and ``segment_steps`` must agree across
        members (a migrated tenant's trajectory is only bit-identical
        when its identity-keyed stream and cadence are).
    :param controller: optional :class:`~evox_tpu.control.Controller`
        for journaled autoscale decisions; one journaling into the
        router's own journal is built when absent.
    :param min_members: autoscale never drains below this many live
        members.
    :param max_members: autoscale never grows past this (``None`` =
        unbounded).
    :param autoscale_shed_rounds: arm the shed-pressure growth trigger —
        this many *consecutive* rounds with fresh sheds requests growth;
        ``None`` disables.
    :param autoscale_burn: arm the SLO-burn growth trigger — the worst
        member burn rate at/over this requests growth; ``None``
        disables.
    :param autoscale_drain: arm scale-down — surplus idle members (zero
        live tenants, nothing queued fleet-wide, more than
        ``min_members`` non-draining) drain first, then retire once
        empty.  Off by default: an unarmed router never shrinks itself.
    :param spawn_member: optional ``index -> ServiceMember`` factory a
        ``grow`` decision calls to add a live member; without it grow
        decisions are journaled and surfaced (``growth_requested``)
        for an external operator.
    :param fleet_dead_after: heartbeat staleness (seconds) after which
        a member is declared dead and its tenants migrate.
    :param fleet_start_grace: grace before a member that never beat is
        judged (forwarded to :class:`~evox_tpu.parallel.FleetHealth`).
    :param store: checkpoint store for the router journal
        (chaos-injectable; defaults to a plain
        :class:`~evox_tpu.utils.CheckpointStore`).
    :param endpoint: arm a router-level introspection endpoint
        (``True`` = OS-assigned port, int = that port) serving the
        fleet-aggregated ``/metrics``, member-verdict ``/healthz``, and
        the router ``/statusz`` section; the gateway rides it when
        attached.
    :param on_event: optional structured-event callback (mirrors the
        daemon's).
    """

    JOURNAL_NAME = "router_journal.jsonl"

    def __init__(
        self,
        root: Union[str, Path],
        members: Sequence[ServiceMember],
        *,
        controller: Any | None = None,
        min_members: int = 1,
        max_members: int | None = None,
        autoscale_shed_rounds: int | None = None,
        autoscale_burn: float | None = None,
        autoscale_drain: bool = False,
        spawn_member: Callable[[int], ServiceMember] | None = None,
        fleet_dead_after: float = 5.0,
        fleet_start_grace: float = 30.0,
        store: Any | None = None,
        endpoint: Union[int, bool, None] = None,
        endpoint_host: str = "127.0.0.1",
        on_event: Callable[[str], None] | None = None,
        compact_records: int | None = None,
        compact_bytes: int | None = None,
        max_replay_seconds: float | None = None,
    ):
        if not members:
            raise ValueError("a router needs at least one member")
        for name, value in (
            ("compact_records", compact_records),
            ("compact_bytes", compact_bytes),
            ("max_replay_seconds", max_replay_seconds),
        ):
            if value is not None and value <= 0:
                raise ValueError(f"{name} must be > 0, got {value}")
        if min_members < 1:
            raise ValueError(f"min_members must be >= 1, got {min_members}")
        if max_members is not None and max_members < min_members:
            raise ValueError(
                f"max_members ({max_members}) < min_members ({min_members})"
            )
        self.root = Path(root)
        self.heartbeat_dir = self.root / "heartbeats"
        self.on_event = on_event
        self._registry = MetricsRegistry()
        self.journal = RequestJournal(
            self.root / self.JOURNAL_NAME,
            store=store,
            registry=self._registry,
        )
        self.members: dict[int, ServiceMember] = {}
        #: Transport per member index — the forward seam.  Replace an
        #: entry with ``FaultyTransport(router.members[i], ...)`` to
        #: inject member-link chaos.
        self.links: dict[int, Any] = {}
        beat_dirs = {
            Path(m.heartbeat.directory).resolve()
            for m in members
            if m.heartbeat is not None
        }
        if len(beat_dirs) > 1:
            raise ValueError(
                f"members beat into different heartbeat directories "
                f"({sorted(map(str, beat_dirs))}); FleetHealth verdicts "
                f"need one shared beat plane"
            )
        if beat_dirs:
            self.heartbeat_dir = beat_dirs.pop()
        seeds: set[Any] = set()
        cadences: set[int] = set()
        roots: set[Path] = set()
        for member in members:
            if member.index in self.members:
                raise ValueError(f"duplicate member index {member.index}")
            root_key = member.root.resolve()
            if root_key in roots or root_key == self.root.resolve():
                raise ValueError(
                    f"member {member.index} root {member.root} is not "
                    f"distinct (each member needs its own journal and "
                    f"tenant namespaces)"
                )
            roots.add(root_key)
            seeds.add(member.daemon.service.seed)
            cadences.add(member.daemon.segment_steps)
            self._register(member)
        if len(seeds) > 1 or len(cadences) > 1:
            raise ValueError(
                f"members disagree on seed ({sorted(map(str, seeds))}) or "
                f"segment_steps ({sorted(cadences)}); migration is only "
                f"bit-identical across identically-configured members"
            )
        if controller is None:
            from ..control import Controller

            controller = Controller(journal=self.journal)
        elif getattr(controller, "journal", None) is None:
            controller.journal = self.journal
        self.controller = controller
        self.min_members = int(min_members)
        self.max_members = None if max_members is None else int(max_members)
        self.autoscale_shed_rounds = (
            None if autoscale_shed_rounds is None else int(autoscale_shed_rounds)
        )
        self.autoscale_burn = (
            None if autoscale_burn is None else float(autoscale_burn)
        )
        self.autoscale_drain = bool(autoscale_drain)
        self.spawn_member = spawn_member
        self.fleet_dead_after = float(fleet_dead_after)
        self.fleet_start_grace = float(fleet_start_grace)
        self.compact_records = (
            None if compact_records is None else int(compact_records)
        )
        self.compact_bytes = (
            None if compact_bytes is None else int(compact_bytes)
        )
        self.max_replay_seconds = (
            None if max_replay_seconds is None else float(max_replay_seconds)
        )
        self.replay_seconds: float | None = None
        self.compactions = 0
        self.compaction_failures = 0
        self.started = False
        self.service = _FleetService(self)
        # tenant_id -> {"uid", "member", "class", "bucket", "spec",
        # "confirmed", "auto"} — the authoritative placement map, always
        # journal-backed (every mutation appends before it applies).
        self._placements: dict[str, dict[str, Any]] = {}
        self._uid_next = 0
        self._dead: set[int] = set()
        self._wedged: set[int] = set()
        self._slow: set[int] = set()
        self._migrations: list[dict[str, Any]] = []
        self._autoscale_events: list[dict[str, Any]] = []
        self.growth_requested = 0
        self._rounds = 0
        self._shed_rounds = 0
        self._last_sheds = 0
        self._link_faults: dict[int, int] = {}
        self._fleet_health: Any | None = None
        self._aggregator = FleetAggregator()
        self.endpoint: IntrospectionEndpoint | None = None
        if endpoint is not None and endpoint is not False:
            self.endpoint = IntrospectionEndpoint(
                metrics=self._metrics_text,
                healthz=self._healthz,
                statusz=self._statusz,
                flight=self._flight_window,
                instrument=self._registry,
                host=endpoint_host,
                port=0 if endpoint is True else int(endpoint),
            )
        # An attached Gateway registers itself here (same seam as the
        # daemon's): /statusz then grows its "gateway" section.
        self.gateway: Any | None = None
        # An attached ChaosConductor registers itself here the same way:
        # /statusz grows a "chaos" section with the live run's plan
        # digest, injected-event and violation counts.
        self.chaos: Any | None = None

    # -- wiring ---------------------------------------------------------------
    # The router is pure host-side orchestration (placement, forwarding,
    # health verdicts) — nothing in it is ever traced or compiled.  The
    # linter's name-based step-family scope pulls start/step and their
    # callees into compiled scope through the call graph, hence the GL005
    # pragmas (the daemon's start/step carry the same note).
    def _register(self, member: ServiceMember) -> None:  # graftlint: disable=GL005
        if member.heartbeat is None:
            from ..parallel.multihost import HostHeartbeat

            member.heartbeat = HostHeartbeat(
                self.heartbeat_dir,
                process_index=member.index,
                extra=member.capacity,
                metrics=member.daemon._registry,
            )
        self.members[member.index] = member
        self.links.setdefault(member.index, member)
        self._fleet_health = None  # world changed; rebuild on next check

    def _event(self, msg: str, *, warn: bool = False) -> None:
        if self.on_event is not None:
            self.on_event(msg)
        elif warn:
            import warnings

            warnings.warn(msg)

    def _inc(self, name: str, help: str = "", **labels: Any) -> None:
        try:
            self._registry.counter(name, help, **labels).inc()
        except Exception:  # pragma: no cover - broken registry
            pass

    def _gauge(self, name: str, value: float, help: str = "") -> None:
        try:
            self._registry.gauge(name, help).set(value)
        except Exception:  # pragma: no cover - broken registry
            pass

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> int:  # graftlint: disable=GL005
        """Start every live member (each replays its own journal), then
        replay the router journal into the placement map and reconcile:
        a journaled placement whose member never admitted the tenant
        (killed post-journal / pre-forward) is forwarded now, so an
        acked decision is never lost and a never-forwarded one completes
        exactly once.  Returns the number of placements restored.
        Idempotent."""
        if self.started:
            return 0
        self.started = True
        if self.endpoint is not None and not self.endpoint.started:
            self.endpoint.start()
        t_replay = time.perf_counter()
        records, damage = self.journal.replay(quarantine=True)
        for note in self.journal.replay_notes:
            # Snapshot-fallback recovery anomalies: the loudness
            # contract — an operator must see every degraded path taken.
            self._inc(
                "evox_router_snapshot_fallbacks_total",
                "Degraded recovery paths taken at router replay "
                "(snapshot fallback, restored swap, gap warnings).",
            )
            self._event(f"router journal recovery: {note}", warn=True)
        if damage is not None:
            self._inc(
                "evox_router_journal_tail_quarantines_total",
                "Damaged router-journal tails quarantined at replay.",
            )
            self._event(
                f"router journal replay: damaged tail ({damage.reason}); "
                f"{damage.bytes_quarantined} bytes quarantined",
                warn=True,
            )
        base = self.journal.snapshot_state
        if base is not None:
            self._event(
                f"router journal replay anchored at snapshot seq "
                f"{self.journal.snapshot_seq} "
                f"({len(records)} suffix records to fold)"
            )
        # Fold snapshot base + record suffix with the same pure fold
        # compaction snapshots through — both cold-start paths compute
        # identical placement maps.
        state, anomalies = fold_router_records(records, base=base)
        for msg in anomalies:
            self._event(f"router journal replay: {msg}", warn=True)
        for tid, placement in state["placements"].items():
            self._placements[tid] = {
                **placement,
                "confirmed": False,
                "auto": bool(placement.get("auto")),
            }
            if placement.get("auto"):
                self._note_migration(placement, replayed=True)
        self._uid_next = max(self._uid_next, int(state["uid_next"]))
        for index in state["drained"]:
            member = self.members.get(int(index))
            if member is not None:
                member.draining = True
        for index in state["retired"]:
            member = self.members.get(int(index))
            if member is not None:
                member.retired = True
                member.draining = False
        restored = len(self._placements)
        for member in self.members.values():
            if not member.retired:
                member.start()
        self._reconcile(auto_only=False)
        # The recovery-time signal: router replay + fold + member
        # replays + reconcile (everything between cold start and
        # serving again).
        self.replay_seconds = time.perf_counter() - t_replay
        self._gauge(
            "evox_recovery_replay_seconds",
            self.replay_seconds,
            "Wall seconds of the last cold-start router recovery "
            "(journal replay + fold + member starts + reconcile).",
        )
        self._journal_gauges()
        if restored:
            self._event(
                f"router replay: {len(records)} records -> {restored} "
                f"placements across {len(self.members)} members "
                f"({self.replay_seconds:.3f}s recovery)"
            )
        return restored

    def close(self) -> None:
        if self.endpoint is not None:
            self.endpoint.stop()
        self.journal.close()
        for member in self.members.values():
            member.close()

    def step(self) -> bool:  # graftlint: disable=GL005
        """One fleet round: consume fleet-health verdicts (migrating any
        dead member's tenants), step every live member, reconcile
        pending migration forwards, and consult the autoscale decider.
        Returns whether any member made progress."""
        self.start()
        self._rounds += 1
        self.poll_fleet()
        busy = False
        for index in sorted(self.members):
            member = self.members[index]
            if index in self._dead or member.retired:
                continue
            busy = member.step() or busy
        # A reconcile forward lands AFTER its member's step this round —
        # the round is not idle, or `run()` would drain out with the
        # freshly re-delivered tenant still queued.
        busy = self._reconcile(auto_only=True) > 0 or busy
        self._consult_autoscale()
        self._maybe_compact()
        return busy

    def run(self, max_rounds: int | None = None) -> None:
        """Drain the fleet (mirrors ``ServiceDaemon.run`` semantics)."""
        rounds = 0
        while max_rounds is None or rounds < max_rounds:
            rounds += 1
            if not self.step():
                return

    # -- placement ------------------------------------------------------------
    def _usable(self, index: int, *, for_placement: bool = False) -> bool:
        member = self.members.get(index)
        if member is None or member.retired or index in self._dead:
            return False
        if for_placement and (
            member.draining or index in self._wedged
        ):
            return False
        return True

    def _owner(self, tenant_id: str) -> ServiceMember | None:
        placement = self._placements.get(tenant_id)
        if placement is None:
            return None
        return self.members.get(placement["member"])

    def _tenant_record(self, tenant_id: str) -> Any:
        member = self._owner(tenant_id)
        if member is not None:
            record = member.daemon.service._tenants.get(tenant_id)
            if record is not None:
                return record
        for member in self.members.values():
            record = member.daemon.service._tenants.get(tenant_id)
            if record is not None:
                return record
        return None

    def _place(self, bucket: str, *, exclude: set[int] | None = None) -> int:
        """Choose a member for one placement: bucket affinity first
        (a live member already running this bucket with a free lane —
        packs stay dense, warm programs get reused), else the
        least-loaded live member; ties break to the lowest index."""
        exclude = exclude or set()
        candidates = [
            i
            for i in sorted(self.members)
            if i not in exclude and self._usable(i, for_placement=True)
        ]
        if not candidates:
            raise AdmissionError(
                "no live member can take placements (all dead, draining, "
                "wedged, or retired); retry after the fleet recovers",
                reason="no-members",
                retry_after_segments=1,
                retry_after_seconds=retry_after_seconds(
                    1, self._last_segment_seconds
                ),
            )
        capacities = {i: self.members[i].capacity() for i in candidates}
        affinity = [
            i
            for i in candidates
            if int(capacities[i].get("free_lanes", {}).get(bucket, 0)) > 0
        ]
        pool = affinity or candidates
        return min(
            pool,
            key=lambda i: (
                int(capacities[i].get("running", 0))
                + int(capacities[i].get("queued", 0)),
                i,
            ),
        )

    def submit(
        self,
        spec: TenantSpec,
        *,
        tenant_class: str = "standard",
        journal_extra: dict[str, Any] | None = None,
    ) -> Any:
        """Place and admit one tenant durably.  The ``uid`` is pinned at
        placement time (the identity the tenant keeps wherever it lands
        or later migrates), the ``placement`` record — carrying the
        gateway's forwarded idempotency key via ``journal_extra`` — is
        fsync'd BEFORE the forward and the ack, and a failed forward
        degrades to a retryable :class:`AdmissionError` whose journaled
        placement is reused (never re-appended, never double-admitted)
        by the retry."""
        self.start()
        tenant_id = spec.tenant_id
        prior = self._placements.get(tenant_id)
        if prior is not None and spec.uid is not None and int(spec.uid) != int(
            prior["uid"]
        ):
            raise AdmissionError(
                f"tenant {tenant_id!r} is placed with uid {prior['uid']}; "
                f"a resubmission may not change identity "
                f"(got uid {spec.uid})",
                reason="uid-mismatch",
            )
        uid = (
            int(prior["uid"])
            if prior is not None
            else (int(spec.uid) if spec.uid is not None else self._uid_next)
        )
        pinned = dataclass_replace(spec, uid=uid)
        bucket = _bucket_label(bucket_key(pinned))
        blob = _encode_spec(pinned)
        was_confirmed = bool(prior and prior.get("confirmed"))
        if was_confirmed:
            if prior["spec"] != blob or prior["class"] != str(tenant_class):
                raise AdmissionError(
                    f"tenant {tenant_id!r} is already admitted; a "
                    f"duplicate id with a different spec or class is a "
                    f"collision (forget the tenant first)",
                    reason="id-collision",
                )
            record = self._tenant_record(tenant_id)
            if record is not None and int(record.uid) == uid:
                # Replay of an acked admission (a retry whose first ack
                # was lost downstream of the router, possibly across a
                # router restart): the journaled placement is the
                # authority — idempotent ack, no append, no forward.
                # The `was_confirmed` guard above means this path is only
                # reachable when a placement record is ALREADY durable, so
                # acking without re-appending is the journal-before-ack
                # contract, not a violation of it.
                return record  # graftlint: disable=GL010
        migrated_from: int | None = None
        if prior is not None and self._usable(prior["member"]):
            # Sticky: resubmissions/retries stay on the owning member
            # even while it drains (affinity beats drain for tenants
            # already resident there).
            target = int(prior["member"])
        else:
            target = self._place(bucket)
            if prior is not None:
                migrated_from = int(prior["member"])
        placement = {
            "tenant_id": tenant_id,
            "uid": uid,
            "member": target,
            "class": str(tenant_class),
            "bucket": bucket,
            "spec": blob,
            "confirmed": False,
            "auto": False,
        }
        if (
            prior is not None
            and not was_confirmed
            and prior["member"] == target
            and prior["spec"] == blob
            and prior["class"] == str(tenant_class)
        ):
            # Retry of an un-acked placement: the journaled decision
            # stands — complete it instead of appending a duplicate.
            placement = prior
        elif migrated_from is not None:
            self._copy_namespace(migrated_from, target, tenant_id)
            self._append_required(
                "migration",
                tenant_id=tenant_id,
                uid=uid,
                member=target,
                **{"from": migrated_from, "class": str(tenant_class)},
                bucket=bucket,
                spec=blob,
                reason="resubmit-dead-owner",
                **(journal_extra or {}),
            )
            self._note_migration(
                {
                    "tenant_id": tenant_id,
                    "uid": uid,
                    "member": target,
                    "from": migrated_from,
                    "reason": "resubmit-dead-owner",
                }
            )
        else:
            self._append_required(
                "placement",
                tenant_id=tenant_id,
                uid=uid,
                member=target,
                **{"class": str(tenant_class)},
                bucket=bucket,
                spec=blob,
                **(journal_extra or {}),
            )
        self._placements[tenant_id] = placement
        self._uid_next = max(self._uid_next, uid + 1)
        # Must-gate analysis cannot see that the one branch skipping
        # `_append_required` above (`placement = prior`) is the retry of an
        # un-acked placement whose record is already durable from the first
        # attempt — every path to this ack has a journaled placement.
        return self._forward_submit(  # graftlint: disable=GL010
            placement, allow_collision=not was_confirmed
        )

    def _append_required(self, kind: str, **data: Any) -> None:
        """Journal one ack-path record; a failed append is a retryable
        refusal (the daemon's submit contract, one plane up)."""
        try:
            self.journal.append(kind, **data)
        except JournalError as e:
            raise AdmissionError(
                f"the router {kind} record could not be made durable ({e})",
                reason="journal-failed",
                retry_after_segments=1,
                retry_after_seconds=retry_after_seconds(
                    1, self._last_segment_seconds
                ),
            ) from e
        self._inc(
            "evox_router_journal_records_total",
            "Router journal records durably appended, by kind.",
            kind=kind,
        )

    def _append_advisory(self, kind: str, **data: Any) -> None:
        try:
            self.journal.append(kind, **data)
        except JournalError as e:
            self._event(
                f"router journal append of advisory {kind!r} failed ({e})",
                warn=True,
            )

    # -- the forward seam -----------------------------------------------------
    def _forward(
        self, index: int, route: str, payload: dict[str, Any]
    ) -> tuple[int, dict[str, Any]]:
        """One mutating forward across the member link.  Transport
        faults (dropped/torn/delayed — anything
        :class:`~evox_tpu.resilience.FaultyTransport` raises) and
        unparseable replies become a structured retryable
        ``member-link`` refusal; the member's own structured refusals
        pass through as ``(status, reply)``."""
        link = self.links.get(index, self.members.get(index))
        body = json.dumps(payload).encode("utf-8")
        try:
            status, _headers, raw = link.request(
                "POST", MEMBER_API_PREFIX + route, {}, body
            )
            reply = json.loads(raw.decode("utf-8"))
            if not isinstance(reply, dict):
                raise ValueError(f"non-object reply: {reply!r}")
        except (ConnectionError, ValueError, UnicodeDecodeError) as e:
            self._link_faults[index] = self._link_faults.get(index, 0) + 1
            self._inc(
                "evox_router_link_faults_total",
                "Member-link forwards lost to transport faults, by member.",
                member=str(index),
            )
            self._event(
                f"member {index} link fault on {route}: "
                f"{type(e).__name__}: {e}",
                warn=True,
            )
            raise AdmissionError(
                f"member {index} link failed ({type(e).__name__}: {e}); "
                f"the decision is journaled — retry lands exactly once",
                reason="member-link",
                retry_after_segments=1,
                retry_after_seconds=retry_after_seconds(
                    1, self._last_segment_seconds
                ),
            ) from e
        return int(status), reply

    def _forward_submit(
        self, placement: dict[str, Any], *, allow_collision: bool
    ) -> Any:
        index = placement["member"]
        status, reply = self._forward(
            index,
            "/submit",
            {"spec": placement["spec"], "tenant_class": placement["class"]},
        )
        member = self.members[index]
        tenant_id = placement["tenant_id"]
        if status == 201:
            placement["confirmed"] = True
            self._inc(
                "evox_router_placements_total",
                "Tenants placed onto members, by member.",
                member=str(index),
            )
            return member.daemon.tenant(tenant_id)
        if status == 409 and allow_collision:
            # An earlier forward of THIS placement landed (reply dropped,
            # duplicated request, or a pre-restart forward): the member
            # holds our tenant under the pinned uid — that IS the ack.
            record = member.daemon.service._tenants.get(tenant_id)
            if record is not None and int(record.uid) == int(placement["uid"]):
                placement["confirmed"] = True
                return record
        raise self._reply_refusal(status, reply, index)

    def _reply_refusal(
        self, status: int, reply: dict[str, Any], index: int
    ) -> Exception:
        reason = str(reply.get("error", "member-error"))
        detail = str(reply.get("detail", reply))
        if status == 404:
            return KeyError(detail)
        if status == 400:
            return ValueError(detail)
        if status == 409 and reason == "conflict":
            return RuntimeError(detail)
        seconds = reply.get("retry_after_seconds")
        if seconds is None and status in (429, 503, 500):
            seconds = retry_after_seconds(1, self._last_segment_seconds)
        return AdmissionError(
            f"member {index} refused: {detail}",
            reason=reason,
            retry_after_segments=reply.get("retry_after_segments"),
            retry_after_seconds=seconds,
        )

    def steer(
        self,
        tenant_id: str,
        *,
        n_steps: int | None = None,
        checkpoint_every: int | None = None,
        max_restarts: int | None = None,
        journal_extra: dict[str, Any] | None = None,
    ) -> dict[str, int]:
        """Forward one durable steer to the owning member (its journal
        acks the knobs before the reply), then journal the router's own
        ``steer`` record carrying the idempotency key so a retry across
        a router restart dedups.  Steers are value-idempotent, so
        forward-then-journal is safe: a duplicate forward collapses at
        the member's replay fold."""
        self.start()
        placement = self._placements.get(tenant_id)
        if placement is None:
            raise KeyError(
                f"unknown tenant {tenant_id!r} (never placed by this router)"
            )
        if not self._usable(placement["member"]):
            raise AdmissionError(
                f"tenant {tenant_id!r} is placed on member "
                f"{placement['member']}, which is down; it migrates at the "
                f"next health check — retry",
                reason="member-down",
                retry_after_segments=1,
                retry_after_seconds=retry_after_seconds(
                    1, self._last_segment_seconds
                ),
            )
        payload: dict[str, Any] = {"tenant_id": tenant_id}
        for name, value in (
            ("n_steps", n_steps),
            ("checkpoint_every", checkpoint_every),
            ("max_restarts", max_restarts),
        ):
            if value is not None:
                payload[name] = int(value)
        status, reply = self._forward(placement["member"], "/steer", payload)
        if status != 200:
            raise self._reply_refusal(status, reply, placement["member"])
        knobs = {k: int(v) for k, v in dict(reply.get("knobs", {})).items()}
        self._append_required(
            "steer",
            tenant_id=tenant_id,
            uid=placement["uid"],
            member=placement["member"],
            **knobs,
            **(journal_extra or {}),
        )
        return knobs

    def park(self, tenant_id: str) -> str:
        """Forward one durable park/withdraw to the owning member (its
        ``evict`` record is the ack); the router's advisory ``park``
        record keeps the placement tail navigable."""
        self.start()
        placement = self._placements.get(tenant_id)
        if placement is None:
            raise KeyError(
                f"unknown tenant {tenant_id!r} (never placed by this router)"
            )
        if not self._usable(placement["member"]):
            raise AdmissionError(
                f"tenant {tenant_id!r} is placed on member "
                f"{placement['member']}, which is down; retry after the "
                f"next health check",
                reason="member-down",
                retry_after_segments=1,
                retry_after_seconds=retry_after_seconds(
                    1, self._last_segment_seconds
                ),
            )
        status, reply = self._forward(
            placement["member"], "/park", {"tenant_id": tenant_id}
        )
        if status != 200:
            raise self._reply_refusal(status, reply, placement["member"])
        self._append_advisory(
            "park",
            tenant_id=tenant_id,
            uid=placement["uid"],
            member=placement["member"],
        )
        return str(reply.get("was", ""))

    def result(self, tenant_id: str) -> Any:
        member = self._owner(tenant_id)
        if member is None:
            raise KeyError(f"unknown tenant {tenant_id!r}")
        return member.daemon.result(tenant_id)

    def tenant(self, tenant_id: str) -> Any:
        record = self._tenant_record(tenant_id)
        if record is None:
            raise KeyError(f"unknown tenant {tenant_id!r}")
        return record

    # -- reconciliation / migration -------------------------------------------
    def _reconcile(self, *, auto_only: bool) -> int:
        """Complete journaled-but-unconfirmed placements.  At start
        (``auto_only=False``) every unconfirmed placement is checked
        against its member — present under the pinned uid means the
        pre-kill forward landed; absent means it never did, so forward
        now (exactly-once: the journal decided, this delivers).  In
        steady state only migration placements auto-retry; a client-
        facing placement whose forward failed waits for the client's
        retry (the ack path stays client-driven).  Returns how many
        forwards were (re)delivered — work queued on a member whose
        round already ran, so the caller's round is not idle."""
        forwarded = 0
        for tenant_id, placement in list(self._placements.items()):
            if placement["confirmed"]:
                continue
            if auto_only and not placement.get("auto"):
                continue
            if not self._usable(placement["member"]):
                continue
            member = self.members[placement["member"]]
            record = member.daemon.service._tenants.get(tenant_id)
            if record is not None and int(record.uid) == int(placement["uid"]):
                placement["confirmed"] = True
                continue
            try:
                self._forward_submit(placement, allow_collision=True)
                forwarded += 1
            except (AdmissionError, KeyError, ValueError, RuntimeError) as e:
                self._event(
                    f"reconcile of {tenant_id!r} on member "
                    f"{placement['member']} deferred: {e}",
                    warn=True,
                )
        return forwarded

    def poll_fleet(self, now: float | None = None) -> Any:  # graftlint: disable=GL005
        """Read the heartbeat plane and act on the verdicts: newly-dead
        members hand their tenants to survivors (journaled migrations);
        wedged/slow members are fenced from new placements.  Returns the
        :class:`~evox_tpu.parallel.FleetReport` (or ``None`` when no
        member heartbeats exist yet)."""
        watched = [
            i
            for i, m in self.members.items()
            if m.heartbeat is not None and not m.retired
        ]
        if not watched or not self.heartbeat_dir.is_dir():
            return None
        world = max(watched) + 1
        from ..parallel.multihost import FleetHealth

        if self._fleet_health is None or self._fleet_health.num_processes != world:
            self._fleet_health = FleetHealth(
                self.heartbeat_dir,
                world,
                dead_after=self.fleet_dead_after,
                start_grace=self.fleet_start_grace,
            )
        # Live knob: an operator (or test) may retune the staleness
        # threshold on a running router; the next verdict honors it.
        self._fleet_health.dead_after = self.fleet_dead_after
        report = self._fleet_health.check(now)
        watched_set = set(watched)
        self._wedged = {
            i for i in report.wedged_hosts if i in watched_set
        } - self._dead
        self._slow = {i for i in report.slow_hosts if i in watched_set} - self._dead
        for index in report.dead_hosts:
            if index in watched_set and index not in self._dead:
                self._dead.add(index)
                reasons = list(
                    getattr(report.verdicts.get(index), "reasons", [])
                )
                self._event(
                    f"member {index} is dead "
                    f"({'; '.join(reasons) or 'stale heartbeat'}); "
                    f"migrating its tenants to survivors",
                    warn=True,
                )
                self._migrate_member(index)
        return report

    def _migrate_member(self, index: int) -> None:
        """Move every tenant placed on a dead member onto survivors:
        copy the per-tenant checkpoint namespace, journal the
        ``migration`` record, and resubmit under the pinned uid — the
        survivor resumes from the last checkpoint bit-identically (the
        PR-7/PR-11 resume contract, cross-daemon)."""
        moved = 0
        for tenant_id, placement in sorted(self._placements.items()):
            if placement["member"] != index:
                continue
            try:
                target = self._place(placement["bucket"], exclude={index})
            except AdmissionError as e:
                self._event(
                    f"tenant {tenant_id!r} is stranded on dead member "
                    f"{index}: {e}",
                    warn=True,
                )
                continue
            self._copy_namespace(index, target, tenant_id)
            try:
                self._append_required(
                    "migration",
                    tenant_id=tenant_id,
                    uid=placement["uid"],
                    member=target,
                    **{"from": index, "class": placement["class"]},
                    bucket=placement["bucket"],
                    spec=placement["spec"],
                    reason="dead-member",
                )
            except AdmissionError as e:
                self._event(
                    f"migration of {tenant_id!r} could not be journaled "
                    f"({e}); it stays on the dead member until a retry",
                    warn=True,
                )
                continue
            self._placements[tenant_id] = {
                **placement,
                "member": target,
                "confirmed": False,
                "auto": True,
            }
            self._note_migration(
                {
                    "tenant_id": tenant_id,
                    "uid": placement["uid"],
                    "member": target,
                    "from": index,
                    "reason": "dead-member",
                }
            )
            try:
                self._forward_submit(
                    self._placements[tenant_id], allow_collision=True
                )
            except (AdmissionError, KeyError, ValueError, RuntimeError) as e:
                self._event(
                    f"migration forward of {tenant_id!r} to member "
                    f"{target} deferred ({e}); reconciled next round",
                    warn=True,
                )
            moved += 1
        if moved:
            self._event(
                f"migrated {moved} tenants off dead member {index}"
            )

    def _copy_namespace(self, source: int, target: int, tenant_id: str) -> None:
        """Bring a tenant's checkpoint namespace to its new member (the
        resume substrate).  Best-effort: a tenant that never
        checkpointed has nothing to copy and resumes from generation
        zero, exactly as a single-daemon restart would."""
        src_member = self.members.get(source)
        dst_member = self.members.get(target)
        if src_member is None or dst_member is None:
            return
        src = src_member.daemon.service.namespace(tenant_id)
        if not src.is_dir():
            return
        dst = dst_member.daemon.service.namespace(tenant_id)
        try:
            shutil.copytree(src, dst, dirs_exist_ok=True)
        except OSError as e:
            self._event(
                f"namespace copy of {tenant_id!r} (member {source} -> "
                f"{target}) failed: {e}; the tenant resumes from its last "
                f"state available on the target",
                warn=True,
            )

    def _note_migration(
        self, data: Mapping[str, Any], *, replayed: bool = False
    ) -> None:
        entry = {
            "tenant_id": data.get("tenant_id"),
            "uid": data.get("uid"),
            "from": data.get("from"),
            "to": data.get("member"),
            "reason": data.get("reason", "replayed" if replayed else ""),
        }
        self._migrations.append(entry)
        del self._migrations[:-_EVENT_TAIL]
        if not replayed:
            self._inc(
                "evox_router_migrations_total",
                "Tenants migrated between members, by reason.",
                reason=str(entry["reason"]),
            )

    # -- autoscale ------------------------------------------------------------
    def _consult_autoscale(self) -> str:  # graftlint: disable=GL005
        """Build this round's autoscale evidence and consult the
        journaled decider: ``grow`` under sustained shed pressure or SLO
        burn, ``drain:<i>``/``retire:<i>`` for surplus idle members
        (drain first — no new placements; retire once drained).  Every
        non-hold action is a journaled, bit-for-bit replayable
        decision."""
        if (
            self.autoscale_shed_rounds is None
            and self.autoscale_burn is None
            and not self.autoscale_drain
        ):
            return "hold"  # nothing armed: the fleet never resizes itself
        live = [
            i
            for i, m in self.members.items()
            if not m.retired and i not in self._dead
        ]
        draining = [i for i in live if self.members[i].draining]
        total_sheds = sum(
            self.members[i].daemon.stats.sheds for i in live
        )
        if total_sheds > self._last_sheds:
            self._shed_rounds += 1
        else:
            self._shed_rounds = 0
        self._last_sheds = total_sheds
        burn = None
        for i in live:
            slo = self.members[i].daemon.slo
            if slo is None:
                continue
            try:
                worst = slo.worst()
            except Exception:  # noqa: BLE001 - advisory signal
                continue
            if worst is not None and (
                burn is None or worst.burn_rate > burn
            ):
                burn = float(worst.burn_rate)
        placed_live: dict[int, int] = {}
        for placement in self._placements.values():
            record = self._tenant_record(placement["tenant_id"])
            status = getattr(
                getattr(record, "status", None), "value", "completed"
            )
            if status != "completed":
                placed_live[placement["member"]] = (
                    placed_live.get(placement["member"], 0) + 1
                )
        drained = [
            i for i in draining if placed_live.get(i, 0) == 0
        ]
        idle = [
            i
            for i in live
            if not self.members[i].draining and placed_live.get(i, 0) == 0
        ]
        queued = sum(
            int(self.members[i].capacity().get("queued", 0)) for i in live
        )
        evidence = {
            "members": len(live),
            "draining": len(draining),
            "min_members": self.min_members,
            "max_members": self.max_members,
            "shed_rounds": self._shed_rounds,
            "shed_sustain": self.autoscale_shed_rounds,
            "burn_rate": burn,
            "burn_enter": self.autoscale_burn,
            "queued": queued,
            "idle_member": (
                min(idle) if idle and self.autoscale_drain else None
            ),
            "drained_member": min(drained) if drained else None,
        }
        action = self.controller.autoscale(
            evidence=evidence, generation=self._rounds
        )
        if action and action != "hold":
            self._apply_autoscale(str(action))
        return str(action or "hold")

    def _apply_autoscale(self, action: str) -> None:  # graftlint: disable=GL005
        entry = {"round": self._rounds, "action": action}
        self._autoscale_events.append(entry)
        del self._autoscale_events[:-_EVENT_TAIL]
        if action == "grow":
            self.growth_requested += 1
            if self.spawn_member is None:
                self._event(
                    "autoscale requests fleet growth (no spawn_member "
                    "factory attached; surfaced for the operator)",
                    warn=True,
                )
                return
            index = max(self.members) + 1
            member = self.spawn_member(index)
            self._register(member)
            member.start()
            self._event(f"autoscale grew the fleet: member {index} joined")
            return
        verb, _, raw = action.partition(":")
        try:
            index = int(raw)
        except ValueError:
            return
        member = self.members.get(index)
        if member is None or member.retired or index in self._dead:
            return
        if verb == "drain":
            self._append_advisory("drain-member", member=index)
            member.draining = True
            self._event(
                f"autoscale drains member {index}: no new placements; "
                f"retires once its tenants finish"
            )
        elif verb == "retire":
            self._append_advisory("retire-member", member=index)
            member.retired = True
            member.draining = False
            if member.heartbeat is not None:
                member.heartbeat.stop()
            self._fleet_health = None
            self._event(
                f"autoscale retired drained member {index} "
                f"(read-only; completed results stay fetchable)"
            )

    # -- journal compaction ----------------------------------------------------
    def _journal_gauges(self) -> None:
        """Publish the journal-growth gauges the compaction SLO watches."""
        self._gauge(
            "evox_journal_bytes",
            self.journal.size_bytes,
            "Router journal file size in bytes.",
        )
        self._gauge(
            "evox_journal_records",
            self.journal.records_since_snapshot,
            "Router journal records since the last snapshot anchor "
            "(the whole history when never compacted).",
        )
        if self.journal.snapshot_at is not None:
            self._gauge(
                "evox_journal_snapshot_age_seconds",
                max(0.0, time.time() - self.journal.snapshot_at),
                "Seconds since the router journal's last snapshot.",
            )

    def _compaction_armed(self) -> bool:
        return (
            self.compact_records is not None
            or self.compact_bytes is not None
            or self.max_replay_seconds is not None
        )

    def _maybe_compact(self) -> None:  # graftlint: disable=GL005
        """Boundary-time router-journal compaction: journal-growth
        evidence → the same pure journaled ``compact`` decider the
        daemon consults → the crash-safe snapshot/swap protocol,
        snapshotting the placement map.  Never raises — a refused or
        failed compaction warns and routing continues on the
        (always-correct) uncompacted journal."""
        self._journal_gauges()
        if not self._compaction_armed():
            return
        evidence = {
            "journal_bytes": self.journal.size_bytes,
            "journal_records": self.journal.records_since_snapshot,
            "live_tenants": len(self._placements),
            "replay_seconds": self.replay_seconds,
            "compact_records": self.compact_records,
            "compact_bytes": self.compact_bytes,
            "max_replay_seconds": self.max_replay_seconds,
        }
        action = self.controller.compact(
            evidence=evidence, generation=self._rounds
        )
        if action == "compact":
            self._compact_journal()

    def _compact_journal(self) -> None:  # graftlint: disable=GL005 host-plane counters; never traced
        """One crash-safe compaction through the journal's protocol,
        folding the placement map with the same pure fold replay uses."""

        def fold(
            base: dict[str, Any] | None, records: list[Any]
        ) -> dict[str, Any]:
            state, _anomalies = fold_router_records(records, base=base)
            return state

        t0 = time.perf_counter()
        try:
            result = self.journal.compact(fold)
        except JournalError as e:
            self.compaction_failures += 1
            self._inc(
                "evox_router_compaction_failures_total",
                "Router-journal compactions that failed (routing "
                "continued on the uncompacted journal).",
            )
            self._event(f"router journal compaction failed ({e})", warn=True)
            return
        self.compactions += 1
        self._inc(
            "evox_router_compactions_total",
            "Successful router-journal compactions.",
        )
        self._journal_gauges()
        self._event(
            f"router journal compacted at seq {result.seq}: "
            f"{result.folded_records} records ({result.bytes_before} "
            f"bytes) folded into {result.snapshot_path.name}; journal "
            f"now {result.bytes_after} bytes"
            + (
                f"; GC'd {len(result.removed)} superseded artifacts"
                if result.removed
                else ""
            )
            + f" ({time.perf_counter() - t0:.3f}s)"
        )

    def _journal_statusz(self) -> dict[str, Any]:
        """The journal/recovery strip ``evoxtop`` renders — same shape
        as the daemon's."""
        snapshot_at = self.journal.snapshot_at
        strip: dict[str, Any] = {
            "bytes": self.journal.size_bytes,
            "records_since_snapshot": self.journal.records_since_snapshot,
            "snapshot_seq": self.journal.snapshot_seq,
            "snapshot_age_seconds": (
                None
                if snapshot_at is None
                else max(0.0, time.time() - snapshot_at)
            ),
            "replay_seconds": self.replay_seconds,
            "compactions": self.compactions,
            "compaction_failures": self.compaction_failures,
            "fallbacks": self.journal.snapshot_fallbacks,
            "armed": self._compaction_armed(),
        }
        if self.controller is not None:
            strip["decisions"] = [
                m
                for m in (
                    d.to_manifest()
                    for d in list(self.controller.decisions)[-40:]
                )
                if m.get("kind") == "compact"
            ][-4:]
        return strip

    # -- gateway-compat surface ----------------------------------------------
    @property
    def _last_segment_seconds(self) -> float | None:
        cadences = [
            m.daemon._last_segment_seconds
            for i, m in self.members.items()
            if self._usable(i) and m.daemon._last_segment_seconds is not None
        ]
        return max(cadences) if cadences else None

    @property
    def slo(self) -> Any | None:
        """The worst-standing member SLO tracker (the gateway scores its
        availability signal somewhere real); ``None`` when no member
        carries one."""
        for i in sorted(self.members):
            if self._usable(i) and self.members[i].daemon.slo is not None:
                return self.members[i].daemon.slo
        return None

    # -- introspection providers (read-only, fail-safe) ------------------------
    def _metrics_text(self) -> str:
        from ..parallel.multihost import read_heartbeats

        beats = (
            read_heartbeats(self.heartbeat_dir)
            if self.heartbeat_dir.is_dir()
            else {}
        )
        if beats:
            report = None
            if self._fleet_health is not None:
                try:
                    report = self._fleet_health.check()
                except Exception:  # noqa: BLE001 - scrape must not throw
                    report = None
            self._aggregator.update(beats, report)
            return self._aggregator.to_prometheus()
        return self._registry.to_prometheus()

    def _healthz(self) -> tuple[bool, dict[str, Any]]:
        dead = sorted(self._dead)
        payload: dict[str, Any] = {
            "router": True,
            "started": self.started,
            "members": len(self.members),
            "live_members": sum(
                1 for i in self.members if self._usable(i)
            ),
            "dead_members": dead,
            "tenants": len(self._placements),
        }
        # Read-only: render the last supervisor's verdicts without
        # re-judging (a probe must not mint migrations — step() does).
        if self._fleet_health is not None:
            try:
                payload.update(self._fleet_health.check().to_json())
            except Exception as e:  # noqa: BLE001 - a probe must answer
                payload["fleet_error"] = f"{type(e).__name__}: {e}"
        healthy = self.started and not dead
        payload["healthy"] = healthy
        return healthy, payload

    def _statusz(self) -> dict[str, Any]:
        members: dict[str, Any] = {}
        placed_counts: dict[int, int] = {}
        for placement in self._placements.values():
            placed_counts[placement["member"]] = (
                placed_counts.get(placement["member"], 0) + 1
            )
        for index in sorted(self.members):
            member = self.members[index]
            if index in self._dead:
                state = "dead"
            elif member.retired:
                state = "retired"
            elif member.draining:
                state = "draining"
            elif index in self._wedged:
                state = "wedged"
            elif index in self._slow:
                state = "slow"
            else:
                state = "ok"
            try:
                capacity = member.capacity()
            except Exception as e:  # noqa: BLE001 - read-only, fail-safe
                capacity = {"error": f"{type(e).__name__}: {e}"}
            members[str(index)] = {
                "state": state,
                "placements": placed_counts.get(index, 0),
                "link_faults": self._link_faults.get(index, 0),
                "capacity": capacity,
            }
        tenants: dict[str, Any] = {}
        counts: dict[str, int] = {}
        for tenant_id, placement in list(self._placements.items()):
            record = self._tenant_record(tenant_id)
            status = getattr(
                getattr(record, "status", None), "value", "unknown"
            )
            counts[status] = counts.get(status, 0) + 1
            tenants[tenant_id] = {
                "status": status,
                "uid": placement["uid"],
                "member": placement["member"],
                "class": placement["class"],
                "bucket": placement["bucket"],
                "generations": int(getattr(record, "generations", 0)),
                "n_steps": int(
                    getattr(getattr(record, "spec", None), "n_steps", 0)
                ),
            }
        out: dict[str, Any] = {
            "schema": OBS_SCHEMA_VERSION,
            "time": time.time(),
            "started": self.started,
            "round_seconds": self._last_segment_seconds,
            "tenants": tenants,
            "tenant_counts": counts,
            "router": {
                "members": members,
                "placements": len(self._placements),
                "uid_next": self._uid_next,
                "rounds": self._rounds,
                "shed_rounds": self._shed_rounds,
                "growth_requested": self.growth_requested,
                "migrations": list(self._migrations[-20:]),
                "autoscale": list(self._autoscale_events[-20:]),
            },
            "journal": self._journal_statusz(),
        }
        if self.controller is not None:
            out["decisions"] = [
                d.to_manifest()
                for d in list(self.controller.decisions)[-20:]
            ]
        if self.gateway is not None:
            try:
                out["gateway"] = self.gateway.statusz_payload()
            except Exception as e:  # noqa: BLE001 - read-only, fail-safe
                out["gateway"] = {"error": f"{type(e).__name__}: {e}"}
        if self.chaos is not None:
            try:
                out["chaos"] = self.chaos.statusz_payload()
            except Exception as e:  # noqa: BLE001 - read-only, fail-safe
                out["chaos"] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def _flight_window(self, tenant_id: str) -> Any:
        member = self._owner(tenant_id)
        if member is None:
            return None
        return member.daemon._flight_window(tenant_id)
