"""Tenant-packed execution: many independent runs, one compiled program.

A :class:`TenantPack` owns a fixed number of **lanes** and one bucket
template :class:`~evox_tpu.workflows.StdWorkflow`.  Every occupied lane
holds one tenant's full workflow state, stacked along a leading lane axis,
and a segment advances ALL lanes together as ONE ``jax.vmap`` of the fused
multi-generation segment program (``StdWorkflow._segment_program`` — the
PR-6 ``lax.scan`` with quarantine, monitor counters, captured history, and
per-lane early stop inside the compiled body).  The host touches the
device once per segment for the whole pack — the amortization that the
regressed per-step ``vmapped_instances`` bench pays per generation.

**The bulkhead.**  Lanes are vmap batch members: the program contains no
cross-lane operation, so one tenant's NaN burst, plateau, or frozen lane
cannot perturb a cotenant's *values* by construction — and because every
lane runs the same barrier-free cond-guarded body
(``SegmentConfig(barrier=False, lane_freeze=True)``), a tenant's
trajectory is the same bits whether its neighbors are healthy, faulty,
frozen, or empty padding (pinned by ``tests/test_service.py`` for PSO and
OpenES).  Three freeze channels share one mechanism:

* **in-scan early stop** — a lane whose state turns unhealthy
  mid-segment freezes itself (its remaining generations are
  ``lax.cond`` no-ops), per lane, because the cond predicate is batched;
* **eviction/quarantine** — the boundary writes the lane's entry in the
  ``frozen`` mask the compiled segment takes as a *traced input*: freezing
  or thawing a lane never recompiles anything;
* **empty lanes** — unoccupied slots are frozen copies of an occupied
  state (``parallel.pad_population`` over the lane axis), so a ragged
  bucket runs the full-width program.

Admission and eviction are **state surgery at segment boundaries**: a
tenant's state is written into / read out of its lane by indexed update,
with the one single-lane ``init_step`` program (compiled once per bucket)
covering fresh admissions.  No admission, retirement, or freeze changes
the segment program.
"""

from __future__ import annotations

import hashlib
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import State
from ..parallel import pad_population

__all__ = ["TenantPack", "assign_fault_lane"]


def _is_prng(leaf: Any) -> bool:
    return isinstance(leaf, jax.Array) and jax.dtypes.issubdtype(
        leaf.dtype, jax.dtypes.prng_key
    )


def assign_fault_lane(state: State, uid: int) -> State:
    """Stamp a tenant's stable uid into every ``fault_lane`` leaf of its
    state (the :class:`~evox_tpu.resilience.FaultyProblem` tenant-keyed
    chaos hook).  A state without such leaves passes through unchanged."""

    def stamp(key_path, leaf):
        names = [
            str(getattr(k, "key", getattr(k, "idx", k))) for k in key_path
        ]
        if names and names[-1] == "fault_lane":
            # full_like, not a scalar: nested (HPO) states carry the leaf
            # per inner instance — every instance of the tenant shares the
            # tenant's uid, and the leading candidate axis must survive.
            return jnp.full_like(leaf, uid)
        return leaf

    return jax.tree_util.tree_map_with_path(stamp, state)


class TenantPack:
    """A fixed-width pack of fault-isolated tenant lanes over one bucket
    template workflow.

    The pack is a *device-side* structure: it owns the stacked lane
    states, the frozen mask, and the compiled programs.  Scheduling —
    which tenant sits in which lane, verdicts, checkpoints — belongs to
    :class:`~evox_tpu.service.OptimizationService`; the pack only enforces
    the mechanics (one program, lane surgery, freeze semantics).

    :param workflow: the bucket template
        :class:`~evox_tpu.workflows.StdWorkflow` (one traced program for
        every lane; per-tenant values live in lane state).
    :param lanes: pack width.  Fixed at construction — the compiled
        segment's batch dimension.
    :param health: optional probe-config object
        (:class:`~evox_tpu.resilience.HealthProbe`); wired into the
        segment config so the in-scan early-stop thresholds mirror the
        boundary verdicts.
    :param early_stop: carry the per-lane unhealthy-state early stop
        in-scan (default True — a poisoned tenant freezes the moment it
        degenerates instead of compounding to the boundary).
    :param flight: batch the flight recorder's per-generation signals
        (:func:`evox_tpu.obs.flight_signals`) out of the vmapped segment
        as ``telemetry["flight"]`` with a leading lane axis — the service
        demuxes one row per tenant, exactly like the history sinks.
    """

    def __init__(
        self,
        workflow: Any,
        lanes: int,
        *,
        health: Any | None = None,
        early_stop: bool = True,
        flight: bool = False,
    ):
        if lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {lanes}")
        if not hasattr(workflow, "_segment_program"):
            raise ValueError(
                f"TenantPack needs a workflow exposing the fused segment "
                f"builder (_segment_program); got "
                f"{type(workflow).__name__}"
            )
        self.workflow = workflow
        self.lanes = int(lanes)
        self.health = health
        # One shape for every lane and every occupancy: barrier-free (the
        # optimization-barrier primitive cannot vmap) and lane_freeze (the
        # frozen mask is a traced input — see the module docstring).
        self.cfg = workflow.segment_config(
            health=health,
            metrics=False,
            stop_on_unhealthy=bool(early_stop),
            barrier=False,
            lane_freeze=True,
            flight=bool(flight),
        )
        self._states: State | None = None
        self._frozen = np.ones((self.lanes,), dtype=bool)
        self.occupants: list[int | None] = [None] * self.lanes
        # Single-lane programs, compiled once per bucket: fresh admissions
        # (init_step) run through these, identically for a pack's first
        # tenant and its sixty-fourth.  The init program captures its
        # monitor sinks like a segment does (the payloads belong to the
        # admitted tenant's monitor, not to the bucket template's host
        # history); the static site identities land in ``_init_meta`` at
        # trace time and stay valid for every cached replay — one config,
        # one trace per pack.
        self._init_meta: list = []
        self._jit_init = jax.jit(self._init_program)
        self._jit_segment = jax.jit(self._vmapped_segment, static_argnums=2)
        # AOT executables installed by prewarm(): {n_steps: callable} for
        # the vmapped segment, plus the single-lane init program.  When
        # present they are dispatched instead of the jit path — a restart
        # that pre-warmed from the persistent executable cache never pays
        # an XLA compile for them.
        self._aot_segment: dict[int, Any] = {}
        self._aot_init: Any | None = None
        # Provenance of each installed program (True = loaded from the
        # persistent cache): a re-prewarm reporting an already-installed
        # program must repeat where it ACTUALLY came from, not claim a
        # cache hit for an in-process compile.
        self._aot_from_cache: dict[Any, bool] = {}

    def _init_program(self, state: State):
        new_state, ys = self.workflow._traced_capture_step(
            state, self._init_meta, True, which="init_step"
        )
        return new_state, ys

    def _vmapped_segment(self, states: State, frozen: jax.Array, n: int):
        return jax.vmap(
            lambda s, f: self.workflow._segment_program(s, n, self.cfg, f)
        )(states, frozen)

    # -- zero cold-start ----------------------------------------------------
    def prewarm(
        self,
        example_state: State,
        n_steps: int | Sequence[int],
        *,
        cache: Any | None = None,
        label: str = "bucket",
    ) -> dict[str, bool]:
        """AOT-compile the pack's programs ahead of the first admission —
        or load them from a persistent
        :class:`~evox_tpu.utils.ExecutableCache` without compiling at all.

        ``example_state`` is one *pre-init* tenant-shaped workflow state
        (what the service's ``_fresh_state`` builds — values are
        irrelevant, only shapes/dtypes key the programs).  The whole pass
        is **abstract**: post-init shapes come from ``jax.eval_shape``
        over the init program (which also captures the init sink
        metadata the telemetry demux needs — abstract evaluation runs no
        device code and, unlike ``jit.lower``, emits no compile-log
        event), and the stacked segment signature is built from
        ``ShapeDtypeStruct`` leaves.  On a cache hit nothing is lowered
        or compiled at all; on a miss the program is lowered, compiled
        once, and persisted.  The loaded/compiled executables are
        installed so :meth:`run_segment` / :meth:`init_tenant` dispatch
        through them — on a warm restart no pack program traces OR
        compiles (``CompileSentinel``-verified by
        ``tools/bench_daemon.py``).

        Returns ``{program_label: loaded_from_cache}``.
        """
        from ..utils.exec_cache import abstract_signature

        lengths = (
            [int(n_steps)]
            if isinstance(n_steps, int)
            else sorted({int(n) for n in n_steps})
        )
        results: dict[str, bool] = {}
        # The segment config changes the compiled program (flight
        # telemetry adds outputs; health metrics add reductions) but not
        # the *input* signature, so it must be part of the cache label:
        # a daemon restarted with the flight recorder newly armed (or
        # disarmed) must not load the other configuration's executable.
        cfg_tag = hashlib.sha256(repr(self.cfg).encode()).hexdigest()[:8]
        init_label = f"pack_init[{label}][lanes={self.lanes}][cfg={cfg_tag}]"
        # Abstract init pass: post-init shapes for the segment signature
        # AND the trace-time capture of the init sink metadata (meta is
        # identical under abstract evaluation — it records static site
        # identities, not values).
        post_init, _ = jax.eval_shape(self._init_program, example_state)
        if self._aot_init is None:
            sig = abstract_signature(example_state)
            # Lowering happens lazily INSIDE the miss path, so a cache
            # hit traces/compiles nothing (get_or_compile wraps the miss
            # in compile_uncached — see utils.exec_cache).
            compile_init = lambda: (  # noqa: E731
                self._jit_init.lower(example_state).compile()
            )
            if cache is None:
                exe, hit = compile_init(), False
            else:
                exe, hit = cache.get_or_compile(
                    init_label, sig, compile_init
                )
            self._aot_init = exe
            self._aot_from_cache["init"] = hit
            results[init_label] = hit
        else:
            results[init_label] = self._aot_from_cache.get("init", False)

        def stack_sds(leaf):
            return jax.ShapeDtypeStruct(
                (self.lanes,) + tuple(leaf.shape), leaf.dtype
            )

        stacked = jax.tree_util.tree_map(stack_sds, post_init)
        frozen = jax.ShapeDtypeStruct((self.lanes,), jnp.bool_)
        for n in lengths:
            if n < 1:
                raise ValueError(f"n_steps must be >= 1, got {n}")
            seg_label = (
                f"pack_segment[{label}][lanes={self.lanes}]"
                f"[cfg={cfg_tag}][n={n}]"
            )
            if n in self._aot_segment:
                results[seg_label] = self._aot_from_cache.get(n, False)
                continue
            sig = abstract_signature(stacked, frozen)
            compile_seg = lambda n=n: (  # noqa: E731
                self._jit_segment.lower(stacked, frozen, n).compile()
            )
            if cache is None:
                exe, hit = compile_seg(), False
            else:
                exe, hit = cache.get_or_compile(seg_label, sig, compile_seg)
            self._aot_segment[n] = exe
            self._aot_from_cache[n] = hit
            results[seg_label] = hit
        return results

    def _dispatch_segment(
        self, states: State, frozen: jax.Array, n: int
    ):
        exe = self._aot_segment.get(n)
        if exe is None:
            return self._jit_segment(states, frozen, n)
        try:
            return exe(states, frozen)
        except (ValueError, TypeError) as e:
            # AOT executables are strict about input placement/layout
            # (same contract as ResilientRunner._get_executable's call
            # wrapper); fall back to traced dispatch, which re-places.
            if "sharding" in str(e).lower() or "layout" in str(e).lower():
                del self._aot_segment[n]
                return self._jit_segment(states, frozen, n)
            raise

    def _dispatch_init(self, state: State):
        if self._aot_init is None:
            return self._jit_init(state)
        try:
            return self._aot_init(state)
        except (ValueError, TypeError) as e:
            if "sharding" in str(e).lower() or "layout" in str(e).lower():
                self._aot_init = None
                return self._jit_init(state)
            raise

    # -- occupancy ----------------------------------------------------------
    @property
    def frozen_mask(self) -> np.ndarray:
        """Copy of the per-lane frozen mask (True = no-op generations)."""
        return self._frozen.copy()

    def free_lanes(self) -> list[int]:
        """Unoccupied lane indices, lowest first."""
        return [i for i, uid in enumerate(self.occupants) if uid is None]

    def occupied_lanes(self) -> list[tuple[int, int]]:
        """``[(lane, uid), ...]`` for every occupied lane."""
        return [
            (i, uid) for i, uid in enumerate(self.occupants) if uid is not None
        ]

    def active_lanes(self) -> list[tuple[int, int]]:
        """Occupied lanes that are not frozen (will actually step)."""
        return [
            (i, uid)
            for i, uid in self.occupied_lanes()
            if not self._frozen[i]
        ]

    # -- lane surgery -------------------------------------------------------
    def init_tenant(self, state: State) -> tuple[State, list, list]:
        """Run the single-lane ``init_step`` program on a freshly set-up
        tenant state (generation 1) — the same compiled program for every
        admission into this bucket, so a tenant's first generation is
        identical however full the pack is.

        Returns ``(state, sink_meta, sinks)``: the captured history
        payloads of the init generation, shaped as length-1 batches so
        they feed straight into ``EvalMonitor.ingest_sinks`` (the caller
        routes them to the admitted tenant's monitor; a template build
        just drops them)."""
        new_state, ys = self._dispatch_init(state)
        sinks = [
            tuple(np.asarray(x)[None] for x in site)
            for site in jax.device_get(ys)
        ]
        return new_state, list(self._init_meta), sinks

    def admit(self, state: State, uid: int, *, frozen: bool = False) -> int:
        """Write a tenant's (post-init or checkpoint-restored) state into
        the first free lane; returns the lane index.  Raises when full —
        capacity is the service's admission-control problem."""
        free = self.free_lanes()
        if not free:
            raise RuntimeError(
                f"pack is full ({self.lanes} lanes); retire or evict a "
                f"tenant before admitting"
            )
        lane = free[0]
        if self._states is None:
            # First admission builds the stacked axis: one real row, padded
            # to the pack width with frozen copies (pad_population repeats
            # the last row — valid values for any program, never stepped).
            stacked = jax.tree_util.tree_map(
                lambda x: jnp.expand_dims(x, 0), state
            )
            self._states, _ = pad_population(stacked, self.lanes)
            if lane != 0:  # pragma: no cover - first free lane is 0 here
                raise AssertionError("first admission must land in lane 0")
        else:
            self._states = self._write_lane(self._states, lane, state)
        self.occupants[lane] = int(uid)
        self._frozen[lane] = bool(frozen)
        return lane

    def _write_lane(self, states: State, lane: int, state: State) -> State:
        def set_row(packed, row):
            if _is_prng(packed):
                # .at[].set on typed PRNG-key arrays is unsupported on
                # this jax; splice the raw key data and re-wrap.
                data = jax.random.key_data(packed)
                row_data = jax.random.key_data(row)
                return jax.random.wrap_key_data(
                    data.at[lane].set(row_data),
                    impl=jax.random.key_impl(packed),
                )
            return packed.at[lane].set(row)

        return jax.tree_util.tree_map(set_row, states, state)

    def lane_state(self, lane: int) -> State:
        """The full workflow state of one lane (a view by-lane slice)."""
        if self._states is None:
            raise RuntimeError("pack has no admitted tenants")
        return jax.tree_util.tree_map(lambda x: x[lane], self._states)

    def write_lane(self, lane: int, state: State) -> None:
        """Overwrite one lane's state in place (restarts, restores)."""
        if self._states is None:
            raise RuntimeError("pack has no admitted tenants")
        self._states = self._write_lane(self._states, lane, state)

    def release(self, lane: int) -> None:
        """Free a lane (retirement/eviction): it freezes and its slot can
        be re-admitted into.  The stale state stays as inert padding."""
        self.occupants[lane] = None
        self._frozen[lane] = True

    def set_frozen(self, lane: int, frozen: bool) -> None:
        """Freeze or thaw one lane — pure mask data, never a recompile."""
        self._frozen[lane] = bool(frozen)

    # -- stepping -----------------------------------------------------------
    def run_segment(self, n_steps: int) -> State:
        """Advance every non-frozen lane ``n_steps`` generations as ONE
        compiled vmapped fused segment; frozen lanes ride along as no-ops.
        Returns the host-side telemetry (one ``device_get`` for the whole
        pack): ``executed``/``stopped`` per lane, the captured history
        batches (demux with
        ``EvalMonitor.ingest_sinks(..., lane=i)``), and ``sink_meta``."""
        if n_steps < 1:
            raise ValueError(f"n_steps must be >= 1, got {n_steps}")
        if self._states is None:
            raise RuntimeError("pack has no admitted tenants")
        states, telemetry = self._dispatch_segment(
            self._states, jnp.asarray(self._frozen), int(n_steps)
        )
        self._states = states
        return jax.device_get(telemetry)

    def check_lanes(
        self,
        probe: Any,
        generation: int = 0,
        lanes: Sequence[int] | None = None,
    ) -> dict[int, Any]:
        """Boundary health verdicts — ``{lane: HealthReport}`` via the
        probe's lane-aware scan, windows keyed on tenant uid (stable
        across lane moves).  ``lanes`` restricts which occupied lanes are
        probed: a frozen lane's unchanged state must not keep feeding its
        stagnation window (it would read as flatlined the moment it
        thaws)."""
        pairs = self.occupied_lanes()
        if lanes is not None:
            allowed = set(lanes)
            pairs = [(l, u) for l, u in pairs if l in allowed]
        if not pairs:
            return {}
        reports = probe.check_lanes(
            self._states, generation=generation, lane_ids=pairs
        )
        return {lane: rep for (lane, _), rep in zip(pairs, reports)}
