"""The multi-tenant optimization service: admission, scheduling, isolation.

:class:`OptimizationService` is the serving layer over
:class:`~evox_tpu.service.TenantPack`: users :meth:`submit` independent
optimization runs (:class:`~evox_tpu.service.TenantSpec`), the service
buckets them by compilation shape, packs each bucket's tenants into one
vmapped fused segment program, and advances every pack segment by segment —
thousands of concurrent runs on one mesh, each with the full per-run
guarantee surface of PRs 1–7 scoped *per tenant*:

* **PRNG isolation** — tenant streams fold the stable uid into the service
  key (identity-keyed, never lane-keyed);
* **telemetry isolation** — each tenant owns an
  :class:`~evox_tpu.workflows.EvalMonitor` fed by the per-lane demux of the
  pack's batched telemetry (``ingest_sinks(lane=...)``), entry-for-entry
  what a solo run records;
* **health isolation** — per-lane verdicts from a lane-aware
  :class:`~evox_tpu.resilience.HealthProbe` (windows keyed by uid), with a
  per-tenant restart budget (rollback to the tenant's newest checkpoint,
  PRNG perturbed by restart index) and lane-granular quarantine once the
  budget is spent;
* **checkpoint isolation** — every tenant has its own namespace directory
  under the service root (``tenants/<tenant_id>/``), written with the
  self-verifying format-2 archives; eviction→readmission resumes
  bit-identically, and the resume scan uses the manifest-only fast mode
  (full digest verification runs on exactly the archive selected);
* **preemption** — a tripped
  :class:`~evox_tpu.resilience.PreemptionGuard` emergency-checkpoints
  EVERY running tenant's namespace at the boundary and raises
  :class:`~evox_tpu.resilience.Preempted`; a fresh service resumes them
  all.

**Overload is loud.**  The waiting queue is bounded: a submission past
``max_queue`` raises :class:`AdmissionError` with a structured reason (and
is recorded in ``stats.rejections``) — the service never silently degrades
admitted tenants to absorb demand.

**Boundaries are the only scheduling points.**  Admission, retirement,
eviction, verdicts, restarts, and checkpoints all happen between segments
(continuous batching for EC); generation budgets are quantized up to whole
segments, identically for every tenant, so a tenant's trajectory is a pure
function of (spec, uid, service configuration) — never of its cotenants.
That is the bulkhead contract ``tests/test_service.py`` pins bit-exactly.
"""

from __future__ import annotations

import dataclasses
import os
import warnings
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import State
from ..obs.plane import Observability, resolve_obs
from ..resilience.health import HealthProbe
from ..resilience.preemption import Preempted, PreemptionGuard
from ..resilience.restart import perturb_prng_keys
from ..resilience.runner import scan_checkpoints
from ..utils.checkpoint import (
    CheckpointError,
    CheckpointStore,
    load_state,
    read_manifest,
    save_state,
)
from ..workflows import EvalMonitor, StdWorkflow
from .pack import TenantPack, assign_fault_lane
from .tenant import (
    TenantRecord,
    TenantSpec,
    TenantStatus,
    bucket_key,
    validate_tenant_id,
)

__all__ = [
    "OptimizationService",
    "AdmissionError",
    "ServiceStats",
    "Rejection",
    "retry_after_seconds",
]


def retry_after_seconds(
    retry_after_segments: int | None, segment_seconds: float | None
) -> float | None:
    """Convert a scheduler retry hint (in segment boundaries — the
    service's scheduling quantum) into wall-clock seconds using the
    **measured** segment cadence.  The one shared conversion: the serving
    daemon's ``stats.rejections`` rows, the raised
    :class:`AdmissionError`, and the gateway's ``Retry-After`` header all
    go through here, so a client and an operator dashboard always read
    the same number.

    Returns ``None`` when either half is unknown (no hint, or no segment
    has been measured yet — a fabricated cadence would be worse than an
    honest "unknown")."""
    if retry_after_segments is None:
        return None
    if not segment_seconds or segment_seconds <= 0:
        return None
    return float(retry_after_segments) * float(segment_seconds)


class AdmissionError(RuntimeError):
    """A submission was refused.  ``reason`` is the structured cause — the
    bounded queue is full (``"queue-full"``), the tenant id collides with
    a live tenant, the spec is unusable, or the serving daemon shed the
    request under overload (``"shed"``).  Overload rejection is the
    contract: beyond its bounds the service refuses loudly instead of
    degrading everyone.

    :ivar reason: machine-readable reject code.
    :ivar retry_after_segments: when set, the scheduler's estimate (in
        segment boundaries — the service's scheduling quantum) of when
        capacity should free up; a client that waits this many boundary
        intervals before retrying lands on the first likely-free slot
        instead of hammering the queue.  ``None`` for rejects that a
        retry cannot fix (id/uid collisions).
    :ivar retry_after_seconds: the same hint in wall-clock seconds via
        the live measured segment cadence
        (:func:`retry_after_seconds` — the serving daemon fills it in);
        ``None`` when no cadence has been measured yet."""

    def __init__(
        self,
        message: str,
        *,
        reason: str,
        retry_after_segments: int | None = None,
        retry_after_seconds: float | None = None,
    ):
        super().__init__(message)
        self.reason = reason
        self.retry_after_segments = (
            None if retry_after_segments is None else int(retry_after_segments)
        )
        self.retry_after_seconds = (
            None if retry_after_seconds is None else float(retry_after_seconds)
        )


class Rejection(tuple):
    """One refused submission: a ``(tenant_id, reason)`` pair (tuple-
    compatible with every pre-existing consumer) carrying the structured
    ``retry_after_segments`` hint — and its wall-clock twin
    ``retry_after_seconds`` (measured-cadence conversion via
    :func:`retry_after_seconds`) — as attributes, so ``stats.rejections``
    records exactly what the raised :class:`AdmissionError` told the
    caller."""

    retry_after_segments: int | None
    retry_after_seconds: float | None

    def __new__(
        cls,
        tenant_id: str,
        reason: str,
        retry_after_segments: int | None = None,
        retry_after_seconds: float | None = None,
    ):
        self = super().__new__(cls, (tenant_id, reason))
        self.retry_after_segments = retry_after_segments
        self.retry_after_seconds = retry_after_seconds
        return self

    def __getnewargs__(self):
        # tuple's default reduce passes the tuple CONTENTS to __new__,
        # which does not match this signature — without this, pickling
        # (fleet transport of ServiceStats) and deepcopy raise TypeError.
        return (
            self[0],
            self[1],
            self.retry_after_segments,
            self.retry_after_seconds,
        )


@dataclass
class ServiceStats:
    """Observable record of what the service did."""

    submitted: int = 0
    admitted: int = 0
    completed: int = 0
    segments_run: int = 0
    rejections: list[Rejection] = field(default_factory=list)
    quarantines: int = 0
    restarts: int = 0
    evictions: int = 0
    readmissions: int = 0
    checkpoints_written: int = 0
    preemptions: int = 0
    early_stops: int = 0


@dataclass
class _Bucket:
    key: tuple
    workflow: StdWorkflow
    pack: TenantPack
    monitor: EvalMonitor  # template (capture plumbing only; history unused)


class OptimizationService:
    """Packs thousands of independent optimization runs onto one mesh with
    per-tenant fault bulkheads.

    Usage::

        svc = OptimizationService("svc_root", lanes_per_pack=64,
                                  segment_steps=16, seed=0)
        svc.submit(TenantSpec("alice-1", PSO(1024, lb, ub), Ackley(),
                              n_steps=400))
        svc.submit(TenantSpec("bob-7", PSO(1024, lb, ub), Ackley(),
                              n_steps=400))      # same bucket, same program
        svc.run()                                 # drain all tenants
        final = svc.result("alice-1")             # full workflow state
        history = svc.tenant("alice-1").monitor.fitness_history

    :param root: service directory; tenant checkpoint namespaces live
        under ``<root>/tenants/<tenant_id>/``.
    :param lanes_per_pack: pack width per compilation bucket (the vmapped
        batch size).  One pack per bucket; tenants beyond the width wait
        in the queue for a free lane (continuous batching).
    :param segment_steps: generations per compiled segment — the
        scheduling quantum: admission, eviction, verdicts, and
        checkpoints happen only at segment boundaries.
    :param max_queue: bound on tenants waiting for a lane; submissions
        past it raise :class:`AdmissionError` (reason ``"queue-full"``).
    :param seed: service PRNG identity; tenant streams are
        ``fold_in(key(seed), uid)``.
    :param health: a :class:`~evox_tpu.resilience.HealthProbe` whose
        detector config drives both the in-scan per-lane early stop and
        the per-lane boundary verdicts; ``None`` builds a default probe
        (non-finite state detection only).
    :param max_restarts: per-tenant restart budget on unhealthy verdicts
        (rollback to the tenant's newest checkpoint with a
        restart-indexed PRNG perturbation); once spent, the lane is
        quarantined (frozen) instead.
    :param checkpoint_every: segments between a tenant's periodic
        namespace checkpoints (1 = every boundary).
    :param preemption: a :class:`~evox_tpu.resilience.PreemptionGuard`
        (or ``True`` for a service-owned one): when tripped, the next
        boundary emergency-checkpoints every running tenant and raises
        :class:`~evox_tpu.resilience.Preempted`.
    :param store: the :class:`~evox_tpu.utils.CheckpointStore` all
        checkpoint file operations route through (chaos-injectable).
    :param early_stop: carry the per-lane unhealthy-state freeze inside
        the compiled segment (default True).
    :param monitor_factory: builds each tenant's host-side monitor AND
        the bucket template monitor; defaults to
        ``EvalMonitor(ordered=False)`` (full fitness history).
    :param on_event: one human-readable line per service event; defaults
        to ``warnings.warn`` for failures and silence otherwise.
    :param obs: the :class:`~evox_tpu.obs.Observability` plane: service
        lifecycle publishes structured ``service`` events, per-tenant
        lifecycle publishes ``tenant`` events carrying ``tenant_id``,
        and ``evox_service_*`` / tenant-labeled ``evox_tenant_*``
        metrics feed the plane's registry (rejections labeled by
        structured reason, per-tenant generations/restarts/quarantines
        by tenant id).  ``None`` builds a default plane; ``False``
        disables instrumentation.  Strictly host-side at boundaries:
        the packed segment programs are identical either way.
    :param controller: optional
        :class:`~evox_tpu.control.Controller` — at every boundary where
        a tenant's threshold verdict reads healthy, the controller
        examines that tenant's flight window (requires a plane-level
        :class:`~evox_tpu.obs.FlightRecorder`, which gives every tenant
        a per-lane recorder) and may fire the graduated degradation
        ladder early: trend verdict → journaled ``tenant`` decision →
        restart (budget permitting) / quarantine / evict
        (``evict_on_storm``).  Decisions are excluded from bit-identity
        like ``num_preemptions``; a controller that fires none leaves
        every tenant bit-identical to ``controller=None``
        (``tests/test_control.py``).  Exception-guarded on both sides —
        a controller failure degrades the tenant to threshold verdicts,
        never wedges the pack.
    """

    def __init__(
        self,
        root: Union[str, Path],
        *,
        lanes_per_pack: int = 8,
        segment_steps: int = 16,
        max_queue: int = 256,
        seed: int = 0,
        health: HealthProbe | None = None,
        max_restarts: int = 1,
        checkpoint_every: int = 1,
        preemption: Union[PreemptionGuard, bool, None] = None,
        store: CheckpointStore | None = None,
        early_stop: bool = True,
        monitor_factory: Callable[[], EvalMonitor] | None = None,
        on_event: Callable[[str], None] | None = None,
        obs: Union[Observability, bool, None] = None,
        controller: Any | None = None,
    ):
        if lanes_per_pack < 1:
            raise ValueError(
                f"lanes_per_pack must be >= 1, got {lanes_per_pack}"
            )
        if segment_steps < 1:
            raise ValueError(
                f"segment_steps must be >= 1, got {segment_steps}"
            )
        if max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        if checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1, got {checkpoint_every}"
            )
        self.root = Path(root)
        self.lanes_per_pack = int(lanes_per_pack)
        self.segment_steps = int(segment_steps)
        self.max_queue = int(max_queue)
        self.seed = int(seed)
        self.health = health if health is not None else HealthProbe()
        self.max_restarts = int(max_restarts)
        self.checkpoint_every = int(checkpoint_every)
        self._owns_guard = preemption is True
        self.preemption: PreemptionGuard | None = (
            PreemptionGuard() if preemption is True else (preemption or None)
        )
        self.store = store if store is not None else CheckpointStore()
        self.early_stop = bool(early_stop)
        self.monitor_factory = monitor_factory or (
            lambda: EvalMonitor(ordered=False)
        )
        self.on_event = on_event
        self.obs = resolve_obs(obs, run_id=Path(root).name)
        self.controller = controller
        if controller is not None:
            controller.bind(self.obs)
        # Durable-eviction seam: a serving daemon installs its own
        # journaled evict here so controller-driven evictions are
        # journal-acked exactly like operator evictions (see
        # :meth:`_evict_for_trend`).
        self.evict_hook: Callable[[str], None] | None = None
        self.stats = ServiceStats()
        self._tenants: dict[str, TenantRecord] = {}
        self._tenants_by_uid: dict[int, TenantRecord] = {}
        self._queue: list[str] = []
        self._buckets: dict[tuple, _Bucket] = {}
        # Post-init load_state templates per (bucket, uid): building one
        # costs a device round through the init program, and the restart
        # path would otherwise pay it on every rollback.
        self._templates: dict[tuple, State] = {}
        self._next_uid = 0
        # Identity-stream roots, one per PRNG key implementation actually
        # used by a tenant (lazily built in _tenant_key; all derive from
        # the same seed).
        self._base_keys: dict[str, jax.Array] = {}

    # -- events -------------------------------------------------------------
    def _event(
        self,
        msg: str,
        *,
        warn: bool = False,
        category: str = "service",
        tenant_id: str | None = None,
        **payload: Any,
    ) -> None:
        """One service event: onto the obs bus (typed, severity intact —
        same bugfix contract as ``ResilientRunner._event``), then through
        the legacy string callback / warning."""
        if self.obs is not None:
            self.obs.event(
                category,
                msg,
                severity="warning" if warn else "info",
                tenant_id=tenant_id,
                **payload,
            )
        if self.on_event is not None:
            self.on_event(msg)
        elif warn:
            warnings.warn(msg)

    def _note(self, record: TenantRecord, msg: str, *, warn: bool = False) -> None:
        record.events.append(msg)
        self._event(
            f"tenant {record.spec.tenant_id}: {msg}",
            warn=warn,
            category="tenant",
            tenant_id=record.spec.tenant_id,
            uid=record.uid,
        )

    # -- metrics ------------------------------------------------------------
    def _inc(self, name: str, help: str = "", n: float = 1, **labels: Any) -> None:
        if self.obs is not None:
            self.obs.counter(name, help, **labels).inc(n)

    # -- admission control --------------------------------------------------
    def submit(self, spec: TenantSpec) -> TenantRecord:
        """Admit one tenant to the bounded queue (or refuse loudly).

        Re-submitting an EVICTED or QUARANTINED tenant's id re-queues it
        for readmission — it will resume from its checkpoint namespace
        bit-identically.  A COMPLETED id must be retired with
        :meth:`forget` first; a QUEUED/RUNNING id is a collision.
        """
        self.stats.submitted += 1
        self._inc(
            "evox_service_submitted_total", "Tenant submissions received."
        )
        existing = self._tenants.get(spec.tenant_id)
        if existing is not None and existing.status in (
            TenantStatus.QUEUED,
            TenantStatus.RUNNING,
        ):
            return self._reject(
                spec,
                "id-collision",
                f"tenant id {spec.tenant_id!r} is already "
                f"{existing.status.value}",
            )
        if existing is not None and existing.status is TenantStatus.COMPLETED:
            return self._reject(
                spec,
                "id-collision",
                f"tenant id {spec.tenant_id!r} already completed; call "
                f"forget() to retire the record before reusing the id",
            )
        if len(self._queue) >= self.max_queue:
            hint = self.retry_hint_segments()
            return self._reject(
                spec,
                "queue-full",
                f"admission queue is at its bound ({self.max_queue}); "
                f"retry after ~{hint} segment boundaries",
                retry_after_segments=hint,
            )
        if existing is not None:
            if spec.uid is not None and spec.uid != existing.uid:
                # The uid IS the tenant's PRNG/chaos/history identity;
                # silently keeping the old one while the caller pinned a
                # different one would diverge any cross-service
                # comparison keyed on the explicit uid.
                return self._reject(
                    spec,
                    "uid-mismatch",
                    f"tenant id {spec.tenant_id!r} is readmission of uid "
                    f"{existing.uid}, but the spec pins uid {spec.uid}; "
                    f"omit uid= (or pass the original) to resume, or "
                    f"forget() the record to start a new identity",
                )
            # Readmission keeps the uid (PRNG / chaos / history identity)
            # and the monitor; only the spec's budget may be refreshed.  A
            # quarantined tenant still holds its frozen lane — release it
            # (its quarantine checkpoint is already on disk), so the
            # readmission resumes from the namespace like any eviction.
            if existing.lane is not None:
                self._buckets[existing.bucket].pack.release(existing.lane)
                existing.lane = None
            if existing.grows and existing.spec.workload == "hpo":
                # Applied growths outlive parking: the record's problem is
                # the GROWN nested problem, while a resubmitted spec
                # necessarily carries the original ungrown one (the grown
                # instance is service-internal).  Keeping the grown
                # problem is what lets readmission resume the grown-shape
                # checkpoints instead of silently skipping them back past
                # the growth at template validation.
                spec = dataclasses.replace(
                    spec, problem=existing.spec.problem
                )
            existing.spec = spec
            existing.status = TenantStatus.QUEUED
            record = existing
            self.stats.readmissions += 1
            self._inc(
                "evox_service_readmissions_total",
                "Evicted/quarantined tenants re-queued.",
            )
            self._note(record, "re-queued for readmission")
        else:
            uid = spec.uid if spec.uid is not None else self._next_uid
            if uid in self._tenants_by_uid:
                return self._reject(
                    spec,
                    "uid-collision",
                    f"uid {uid} is already assigned to another tenant",
                )
            self._next_uid = max(self._next_uid, uid + 1)
            record = TenantRecord(
                spec=spec, uid=uid, monitor=self.monitor_factory()
            )
            if self.obs is not None and self.obs.flight is not None:
                # One flight recorder per tenant, bundles under the
                # plane recorder's dir in the tenant's own namespace;
                # subscribed to the bus so this tenant's warning events
                # (restart, quarantine, early stop, preemption) dump its
                # own last-K-generation window.
                record.flight = self.obs.flight.for_tenant(spec.tenant_id)
                self.obs.bus.add_sink(record.flight)
            self._tenants[spec.tenant_id] = record
            self._tenants_by_uid[uid] = record
            self._note(record, f"queued (uid {uid})")
        self._queue.append(spec.tenant_id)
        return record

    def _reject(
        self,
        spec: TenantSpec,
        reason: str,
        detail: str,
        *,
        retry_after_segments: int | None = None,
        retry_after_seconds: float | None = None,
    ):
        self.stats.rejections.append(
            Rejection(
                spec.tenant_id,
                reason,
                retry_after_segments,
                retry_after_seconds,
            )
        )
        self._inc(
            "evox_service_rejections_total",
            "Submissions refused, by structured reason.",
            reason=reason,
        )
        self._event(
            f"rejected tenant {spec.tenant_id!r} ({reason}): {detail}",
            warn=True,
            tenant_id=spec.tenant_id,
            reason=reason,
        )
        raise AdmissionError(
            f"submission of tenant {spec.tenant_id!r} refused "
            f"({reason}): {detail}",
            reason=reason,
            retry_after_segments=retry_after_segments,
            retry_after_seconds=retry_after_seconds,
        )

    def retry_hint_segments(self) -> int:
        """Scheduler estimate of how many segment boundaries until a lane
        frees: the nearest running tenant's remaining whole segments (1
        when nothing is running — the next round admits directly).  The
        structured ``retry_after_segments`` hint on overload rejections."""
        remaining = [
            -(-max(0, r.spec.n_steps - r.generations) // self.segment_steps)
            for r in self._tenants.values()
            if r.status is TenantStatus.RUNNING
        ]
        return max(1, min(remaining)) if remaining else 1

    # -- tenant accessors ---------------------------------------------------
    def tenant(self, tenant_id: str) -> TenantRecord:
        """The runtime record of one tenant (KeyError for unknown ids)."""
        return self._tenants[tenant_id]

    def result(self, tenant_id: str) -> State:
        """A tenant's full workflow state: the final state for COMPLETED
        tenants, the live lane state for RUNNING/QUARANTINED ones."""
        record = self._tenants[tenant_id]
        if record.result is not None:
            return record.result
        if record.lane is None:
            raise RuntimeError(
                f"tenant {tenant_id!r} is {record.status.value} and holds "
                f"no lane; resume it (submit again) or read its checkpoints"
            )
        return self._buckets[record.bucket].pack.lane_state(record.lane)

    def forget(self, tenant_id: str, *, purge: bool = False) -> None:
        """Retire a COMPLETED/EVICTED/QUARANTINED tenant's record.  A
        quarantined tenant still holds its frozen lane — it is released
        here, so retiring the record returns the capacity to the pack.

        With ``purge=False`` (default) the checkpoint namespace stays on
        disk (resumable by a later submit of the same id).  With
        ``purge=True`` the tenant's checkpoint namespace and flight dir
        are GC'd through the store — the daemon passes it once the
        ``retire`` journal record is durable (the durable-successor
        rule), closing the retired-tenants-leak: without it, disk grows
        with *lifetime* churn instead of *live* tenants.  GC is advisory
        and store-routed: a read-only store's refusal (non-primary
        process) leaves the files for the primary to reap."""
        record = self._tenants.get(tenant_id)
        if record is None:
            return
        if record.status in (TenantStatus.QUEUED, TenantStatus.RUNNING):
            raise RuntimeError(
                f"tenant {tenant_id!r} is {record.status.value}; evict it "
                f"before forgetting"
            )
        if record.lane is not None:
            self._buckets[record.bucket].pack.release(record.lane)
            record.lane = None
        self._templates.pop((record.bucket, record.uid), None)
        self._tenants_by_uid.pop(record.uid, None)
        del self._tenants[tenant_id]
        if record.flight is not None and self.obs is not None:
            # Detach the tenant's postmortem trigger with its record —
            # a forgotten tenant's recorder must not keep dumping on a
            # reused tenant id's events.
            self.obs.bus.remove_sink(record.flight)
        if self.obs is not None:
            # Retire the tenant's metric series with its record: tenant
            # churn must not grow the registry (and every snapshot /
            # heartbeat payload) without bound.
            self.obs.registry.remove_labeled("tenant_id", tenant_id)
        if purge:
            self._purge_tenant_dirs(tenant_id, record)

    def _purge_tenant_dirs(self, tenant_id: str, record: TenantRecord) -> None:
        """Reclaim a retired tenant's disk: the checkpoint namespace and
        the labeled flight dir, bottom-up through the store seam (every
        unlink chaos-injectable and refused cleanly by a read-only
        store).  Advisory — a failed unlink leaves orphans a later purge
        re-reaps, never an error on the retire path."""
        targets = [self.namespace(tenant_id)]
        if record.flight is not None:
            targets.append(record.flight.dir)
        elif self.obs is not None and self.obs.flight is not None:
            targets.append(self.obs.flight.dir / tenant_id)
        for root in targets:
            if not root.is_dir():
                continue
            for dirpath, dirnames, filenames in os.walk(root, topdown=False):
                for name in filenames:
                    try:
                        self.store.unlink(Path(dirpath) / name)
                    except OSError:
                        pass
                for name in dirnames:
                    try:
                        os.rmdir(Path(dirpath) / name)
                    except OSError:
                        pass
            try:
                os.rmdir(root)
            except OSError:
                pass

    def withdraw(
        self, tenant_id: str, *, to_status: TenantStatus | None = None
    ) -> None:
        """Remove a QUEUED tenant from the admission queue before it ever
        occupies a lane.

        With ``to_status=None`` (default) the record is dropped entirely —
        the un-admit the serving daemon uses when a submission's journal
        record could not be made durable (an acked-but-unjournaled tenant
        would be silently lost by a crash).  With
        ``to_status=TenantStatus.EVICTED`` the record is kept parked
        (resumable from its namespace via a later :meth:`submit`) — the
        replay path for tenants whose journaled state is "evicted"."""
        record = self._tenants.get(tenant_id)
        if record is None or record.status is not TenantStatus.QUEUED:
            raise RuntimeError(
                f"tenant {tenant_id!r} is not QUEUED"
                + (
                    f" (status {record.status.value})"
                    if record is not None
                    else " (unknown id)"
                )
            )
        self._queue = [t for t in self._queue if t != tenant_id]
        if to_status is not None:
            record.status = to_status
            self._note(record, f"withdrawn from queue ({to_status.value})")
            return
        self._templates.pop((record.bucket, record.uid), None)
        self._tenants_by_uid.pop(record.uid, None)
        del self._tenants[tenant_id]
        if record.flight is not None and self.obs is not None:
            self.obs.bus.remove_sink(record.flight)
        if self.obs is not None:
            self.obs.registry.remove_labeled("tenant_id", tenant_id)
        self._note(record, "withdrawn from queue (record dropped)")

    # -- checkpoint namespaces ----------------------------------------------
    def namespace(self, tenant_id: str) -> Path:
        """The tenant's private checkpoint directory.  The id is
        re-validated as a safe path component here (defense in depth —
        every :class:`TenantSpec` already validated at construction, but
        this method is also reachable with raw strings)."""
        validate_tenant_id(tenant_id)
        return self.root / "tenants" / tenant_id

    def _ckpt_path(self, record: TenantRecord, generation: int) -> Path:
        return self.namespace(record.spec.tenant_id) / (
            f"ckpt_{generation:08d}.npz"
        )

    def _checkpoint_tenant(
        self,
        record: TenantRecord,
        state: State,
        *,
        emergency: bool = False,
        reason: str | None = None,
    ) -> None:
        ns = self.namespace(record.spec.tenant_id)
        ns.mkdir(parents=True, exist_ok=True)
        from ..precision import precision_tag, resolve_key_impl

        metadata: dict[str, Any] = {
            "tenant_id": record.spec.tenant_id,
            "uid": record.uid,
            "tenant_status": record.status.value,
            "tenant_restarts": record.restarts,
            "lane_health_window": list(self.health.lane_window(record.uid)),
            # Numerics identity (remesh-style guard): readmission refuses
            # a cross-policy / cross-impl resume before touching a leaf.
            "precision": precision_tag(record.spec.precision),
            "key_impl": resolve_key_impl(record.spec.key_impl),
        }
        if emergency:
            metadata.update(
                preempted=True, preemption_reason=reason or "preempted"
            )
        path = self._ckpt_path(record, record.generations)
        try:
            save_state(
                path,
                state,
                generation=record.generations,
                metadata=metadata,
                store=self.store,
                durable=emergency,
            )
        except (OSError, RuntimeError, ValueError) as e:
            self._note(
                record,
                f"checkpoint write of {path.name} failed "
                f"({type(e).__name__}: {e}); previous checkpoint remains "
                f"the resume point",
                warn=True,
            )
            return
        record.segments_since_checkpoint = 0
        self.stats.checkpoints_written += 1
        self._inc(
            "evox_service_checkpoints_written_total",
            "Tenant-namespace checkpoints published.",
        )

    # -- tenant state construction -------------------------------------------
    def _tenant_key(self, uid: int, key_impl: str | None = None) -> jax.Array:
        # Identity-keyed stream: stable across lanes, packs, and
        # readmissions (the GL006 discipline, applied to tenants).  One
        # base key per PRNG implementation, derived from the SAME seed:
        # an rbg tenant's stream is a function of (seed, impl, uid) only
        # — never of which cotenants or lanes exist — so an rbg tenant
        # beside a threefry tenant finishes bit-identical to the same
        # tenant solo in either impl.
        from ..precision import make_key, resolve_key_impl

        impl = resolve_key_impl(key_impl)
        base = self._base_keys.get(impl)
        if base is None:
            base = self._base_keys[impl] = make_key(self.seed, impl)
        return jax.random.fold_in(base, jnp.uint32(uid))

    def _fresh_state(self, bucket: _Bucket, record: TenantRecord) -> State:
        """A tenant's pre-init state, built exactly like
        ``StdWorkflow.setup`` but from the tenant's identity-folded key,
        with the uid stamped into the monitor instance id and every
        ``fault_lane`` chaos leaf."""
        wf = bucket.workflow
        algo_key, prob_key, mon_key = jax.random.split(
            self._tenant_key(record.uid, record.spec.key_impl), 3
        )
        mon_state = wf.monitor.setup(mon_key)
        if "instance_id" in mon_state:
            mon_state = mon_state.replace(
                instance_id=jnp.asarray(record.uid, jnp.int32)
            )
        # apply_precision: the storage form (narrow mapped leaves) —
        # exactly the layout wf.setup() would have produced.
        state = wf.apply_precision(
            State(
                algorithm=wf.algorithm.setup(algo_key),
                problem=wf.problem.setup(prob_key),
                monitor=mon_state,
            )
        )
        return assign_fault_lane(state, record.uid)

    def _resume_state(
        self, bucket: _Bucket, record: TenantRecord
    ) -> tuple[State, int] | None:
        """Newest usable checkpoint of the tenant's namespace, or None.

        The scan is the manifest-only fast path (a service root holds one
        directory per tenant, hundreds of archives in aggregate; hashing
        every byte of every candidate on every readmission is the O(N·B)
        cost the fast mode exists to avoid) — the selected archive is then
        FULLY digest-verified at load.  Corrupt candidates are quarantined
        ``*.corrupt`` exactly like the runner's scan."""
        ns = self.namespace(record.spec.tenant_id)
        if not ns.is_dir():
            return None
        # One template build per (bucket, tenant): it costs a device pass
        # through the init program, and the rollback-restart path resumes
        # repeatedly.  Tenant-specific (not per-bucket) because
        # allow_missing restores keep TEMPLATE values for leaves a
        # pre-upgrade checkpoint lacks — those must be this tenant's.
        tkey = (bucket.key, record.uid)
        template = self._templates.get(tkey)
        if template is None:
            template, _, _ = bucket.pack.init_tenant(
                self._fresh_state(bucket, record)
            )
            self._templates[tkey] = template
        candidates, rejected = scan_checkpoints(
            ns, verify="manifest", quarantine=True, store=self.store
        )
        for path, why, quarantined in rejected:
            self._note(
                record,
                f"resume scan skipped {path.name}: {why}"
                + (" (quarantined)" if quarantined else ""),
                warn=True,
            )
        for gen, path in reversed(candidates):
            try:
                manifest = read_manifest(path)
                state = load_state(
                    path,
                    template,
                    allow_missing=True,
                    verify=True,
                    precision=record.spec.precision,
                    key_impl=record.spec.key_impl,
                )
            except FileNotFoundError:
                continue
            except (CheckpointError, ValueError) as e:
                self._note(
                    record,
                    f"resume skipped {path.name}: {e}",
                    warn=True,
                )
                continue
            self.health.restore_lane(
                record.uid, manifest.get("lane_health_window", [])
            )
            # max(): a rollback restart reloads a checkpoint written
            # BEFORE the restart fired — adopting its (lower) count would
            # hand the tenant an unspendable budget and loop forever.
            record.restarts = max(
                record.restarts, int(manifest.get("tenant_restarts", 0))
            )
            self._note(record, f"resumed from {path.name} (generation {gen})")
            return state, gen
        return None

    # -- buckets ------------------------------------------------------------
    def _bucket_for(self, spec: TenantSpec) -> _Bucket:
        bkey = bucket_key(spec)
        bucket = self._buckets.get(bkey)
        if bucket is None:
            monitor = self.monitor_factory()
            workflow = StdWorkflow(
                spec.algorithm,
                spec.problem,
                monitor=monitor,
                solution_transform=spec.solution_transform,
                precision=spec.precision,
                key_impl=spec.key_impl,
            )
            pack = TenantPack(
                workflow,
                self.lanes_per_pack,
                health=self.health,
                early_stop=self.early_stop,
                flight=(
                    self.obs is not None and self.obs.flight is not None
                ),
            )
            bucket = _Bucket(
                key=bkey, workflow=workflow, pack=pack, monitor=monitor
            )
            self._buckets[bkey] = bucket
            self._event(
                f"new bucket {bkey[0]} pop={bkey[1]} dim={bkey[2]} "
                f"({self.lanes_per_pack} lanes)"
            )
        return bucket

    # -- scheduling ---------------------------------------------------------
    # OptimizationService.step() is a HOST-side scheduling round (the pack
    # dispatches the compiled programs); the linter's name-based step-family
    # scope pulls its closure in, but nothing here is ever traced.
    def _admit_pending(self) -> None:  # graftlint: disable=GL005
        """Fill free lanes from the queue (boundary-only admission)."""
        still_waiting: list[str] = []
        for tenant_id in self._queue:
            record = self._tenants[tenant_id]
            bucket = self._bucket_for(record.spec)
            if not bucket.pack.free_lanes():
                still_waiting.append(tenant_id)
                continue
            resumed = self._resume_state(bucket, record)
            if resumed is not None:
                state, generations = resumed
                # The resume point can sit BEHIND history the monitor
                # already recorded (an eviction whose final checkpoint
                # write failed falls back to an older archive): prune the
                # tail past it, or the replay's tags would collide with
                # the stale entries.
                if record.monitor is not None and hasattr(
                    record.monitor, "truncate_history"
                ):
                    record.monitor.truncate_history(generations)
                if generations >= record.spec.n_steps:
                    # Budget already met at the resume point (a refreshed
                    # smaller budget, or a completed tenant's surviving
                    # namespace): return the resumed state as the result
                    # instead of burning a lane on a whole extra segment.
                    record.bucket = bucket.key
                    record.generations = generations
                    record.status = TenantStatus.COMPLETED
                    record.result = jax.device_get(state)
                    self.stats.admitted += 1
                    self.stats.completed += 1
                    self._inc(
                        "evox_service_admitted_total",
                        "Tenants admitted to a lane (or completed at "
                        "admission).",
                    )
                    self._inc(
                        "evox_tenant_completed_total",
                        "Tenant runs completed.",
                        tenant_id=record.spec.tenant_id,
                    )
                    self._note(
                        record,
                        f"resumed at generation {generations}, already at "
                        f"or past the n_steps={record.spec.n_steps} "
                        f"budget — completed without occupying a lane",
                    )
                    continue
            else:
                state, init_meta, init_sinks = bucket.pack.init_tenant(
                    self._fresh_state(bucket, record)
                )
                generations = 1
                self.health.reset_lane(record.uid)
                if init_sinks and record.monitor is not None:
                    # The init generation's history belongs to THIS
                    # tenant's monitor, exactly like a solo run's first
                    # callback.
                    record.monitor.ingest_sinks(
                        init_meta, init_sinks, np.int32(1)
                    )
            record.bucket = bucket.key
            record.generations = generations
            record.lane = bucket.pack.admit(state, record.uid)
            record.status = TenantStatus.RUNNING
            record.segments_since_checkpoint = 0
            self.stats.admitted += 1
            self._inc(
                "evox_service_admitted_total",
                "Tenants admitted to a lane (or completed at admission).",
            )
            self._note(
                record,
                f"admitted to lane {record.lane} at generation "
                f"{generations}",
            )
            if resumed is None:
                # The post-init state is the tenant's first resume point:
                # a fresh tenant killed before its first boundary must not
                # restart from scratch while cotenants move on.
                self._checkpoint_tenant(record, state)
        self._queue = still_waiting

    def evict(self, tenant_id: str) -> None:
        """Checkpoint a RUNNING/QUARANTINED tenant's lane to its namespace
        and free the lane (boundary semantics: call between :meth:`step`
        calls).  Readmission (:meth:`submit` with the same id) resumes
        bit-identically from the checkpoint."""
        record = self._tenants[tenant_id]
        if record.lane is None:
            raise RuntimeError(
                f"tenant {tenant_id!r} is {record.status.value} and holds "
                f"no lane"
            )
        bucket = self._buckets[record.bucket]
        self._checkpoint_tenant(record, bucket.pack.lane_state(record.lane))
        bucket.pack.release(record.lane)
        record.lane = None
        record.status = TenantStatus.EVICTED
        self.stats.evictions += 1
        self._inc(
            "evox_service_evictions_total",
            "Tenants evicted to their checkpoint namespace.",
        )
        self._note(record, "evicted (checkpointed; lane freed)")

    def _handle_preemption(self) -> None:
        reason = self.preemption.reason or "preempted"
        for record in self._tenants.values():
            if record.lane is None:
                continue
            bucket = self._buckets[record.bucket]
            state = bucket.pack.lane_state(record.lane)
            mon = bucket.workflow.monitor
            if "monitor" in state:
                state = state.replace(
                    monitor=mon.record_preemption(state["monitor"])
                )
                bucket.pack.write_lane(record.lane, state)
            self._checkpoint_tenant(
                record, state, emergency=True, reason=reason
            )
            # Leave the record in the EVICTED shape (lane freed, resume
            # point on disk): "resubmit the same tenants" then works on
            # THIS instance exactly like on a fresh one over the same
            # root — without this, the records would sit RUNNING and
            # every resubmission would bounce off the id-collision guard.
            bucket.pack.release(record.lane)
            record.lane = None
            record.status = TenantStatus.EVICTED
            self._note(record, f"preempted ({reason}); lane freed")
        self.stats.preemptions += 1
        self._inc(
            "evox_service_preemptions_total",
            "Service-wide graceful preemption stops.",
        )
        self._event(
            f"preempted ({reason}); emergency checkpoints published for "
            f"every running tenant",
            warn=True,
            category="preemption",
            reason=reason,
        )
        raise Preempted(
            f"service preempted ({reason}); every running tenant's "
            f"namespace holds an emergency checkpoint — resubmit the same "
            f"tenants to resume bit-identically",
            reason=reason,
        )

    def step(self) -> bool:
        """One scheduling round: boundary work (preemption check,
        admissions), then one fused segment per pack with active lanes,
        then per-lane boundary work (telemetry demux, verdicts,
        restarts/quarantine, retirement, checkpoints).  Returns whether
        any lane actually stepped."""
        if self.preemption is not None and self.preemption.triggered:
            self._handle_preemption()
        self._admit_pending()
        stepped_any = False
        # Snapshot: boundary work can CREATE buckets mid-iteration (the
        # hpo-grow re-key admits the grown tenant into a new bucket); the
        # new bucket steps from the next round.
        for bucket in list(self._buckets.values()):
            if not bucket.pack.active_lanes():
                continue
            telemetry = bucket.pack.run_segment(self.segment_steps)
            self.stats.segments_run += 1
            self._inc(
                "evox_service_segments_total",
                "Packed fused segments dispatched.",
            )
            stepped_any = True
            self._boundary(bucket, telemetry)
        # Late admissions: lanes freed by this round's retirements.
        if self._queue:
            self._admit_pending()
        return stepped_any

    def run(self, max_rounds: int | None = None) -> None:
        """Drain the service: step until no lane can make progress (all
        tenants COMPLETED, QUARANTINED, or EVICTED and the queue cannot be
        placed).  ``max_rounds`` bounds the loop for tests.

        Installs the preemption guard (when configured) for the duration,
        exactly like ``ResilientRunner.run``; a service-owned guard
        (``preemption=True``) is reset first so a previous run's trip
        cannot re-fire."""
        installed_guard = False
        if self.preemption is not None:
            if self._owns_guard:
                self.preemption.reset()
            if not self.preemption.installed:
                self.preemption.install()
                installed_guard = True
        try:
            rounds = 0
            while True:
                if max_rounds is not None and rounds >= max_rounds:
                    return
                progressed = self.step()
                rounds += 1
                if not progressed and not self._queue:
                    return
                if not progressed and self._queue:
                    # Queue waits on lanes that no longer free themselves
                    # (every occupant quarantined/complete but
                    # un-forgotten): admission had its chance in step();
                    # nothing will change.
                    return
        finally:
            if installed_guard:
                self.preemption.uninstall()

    # -- boundary work ------------------------------------------------------
    # Host-side boundary work on device_get-ed telemetry (see the
    # step-family scope note above _admit_pending).
    def _boundary(self, bucket: _Bucket, telemetry: Any) -> None:  # graftlint: disable=GL002
        executed = np.asarray(telemetry["executed"])
        stopped = np.asarray(telemetry["stopped"])
        meta_pairs = StdWorkflow.sink_meta_pairs(telemetry)
        sinks = telemetry["sinks"] if "sinks" in telemetry else ()
        was_active = {
            lane for lane, _ in bucket.pack.occupied_lanes()
            if executed[lane] > 0 or not bucket.pack.frozen_mask[lane]
        }
        for lane, uid in bucket.pack.occupied_lanes():
            if lane not in was_active:
                continue
            record = self._record_by_uid(uid)
            record.generations += int(executed[lane])
            record.segments_since_checkpoint += 1
            if executed[lane]:
                self._inc(
                    "evox_tenant_generations_total",
                    "Generations completed, per tenant.",
                    n=int(executed[lane]),
                    tenant_id=record.spec.tenant_id,
                )
            if sinks and record.monitor is not None:
                record.monitor.ingest_sinks(
                    meta_pairs, sinks, np.asarray(telemetry["executed"]),
                    lane=lane,
                )
            if record.spec.workload == "hpo" and executed[lane]:
                from ..hpo.nested import find_nested

                nested = find_nested(record.spec.problem)
                if nested is not None:
                    # One outer generation of an HPO tenant executes a
                    # whole inner ladder: candidates x repeats x
                    # iterations inner generations.
                    self._inc(
                        "evox_hpo_inner_generations_total",
                        "Inner generations executed by packed HPO "
                        "tenants (candidates x repeats x iterations per "
                        "outer generation).",
                        n=int(executed[lane])
                        * nested.inner_generations_per_eval(),
                        tenant_id=record.spec.tenant_id,
                    )
            if (
                record.flight is not None
                and "flight" in telemetry
                and executed[lane]
            ):
                # Lane-demuxed flight feed, BEFORE the verdicts below:
                # a restart/quarantine note must dump a window that
                # includes this segment's rows.  record.generations was
                # already advanced, so the segment started executed[lane]
                # generations earlier.
                record.flight.record_rows(
                    telemetry["flight"],
                    int(executed[lane]),
                    start_generation=(
                        record.generations - int(executed[lane])
                    ),
                    lane=lane,
                )
            if bool(stopped[lane]) and int(executed[lane]) < self.segment_steps:
                self.stats.early_stops += 1
                self._inc(
                    "evox_tenant_early_stops_total",
                    "In-scan lane freezes, per tenant.",
                    tenant_id=record.spec.tenant_id,
                )
                self._note(
                    record,
                    f"in-scan early stop at generation "
                    f"{record.generations}: lane froze mid-segment",
                    warn=True,
                )
        # Verdicts on the post-segment states (one vmapped scan for the
        # whole pack); windows keyed by uid.  Only lanes that stepped are
        # probed — frozen lanes must not feed their stagnation windows.
        reports = bucket.pack.check_lanes(self.health, lanes=was_active)
        for lane, report in reports.items():
            record = self._record_by_uid(bucket.pack.occupants[lane])
            report.generation = record.generations
            if record.generations >= record.spec.n_steps:
                self._complete(bucket, record)
                continue
            if (
                report.healthy
                and record.spec.workload == "hpo"
                and record.spec.grow is not None
                and self.controller is not None
            ):
                # Elastic inner-population ladder (evox_tpu.hpo): a
                # stagnating inner run fires a journaled hpo-grow
                # decision and the tenant re-keys to the grown bucket at
                # this boundary.  A fired growth IS this boundary's
                # verdict for the tenant; otherwise it falls through to
                # the ordinary trend/checkpoint handling below.
                if self._maybe_grow_hpo(bucket, record):
                    continue
            if (
                report.healthy
                and self.controller is not None
                and self.controller.trend_enabled
            ):
                # Trend overlay on a threshold-healthy lane: the
                # controller reads the tenant's flight window and may
                # fire the degradation ladder early.  An unhealthy
                # threshold verdict below always wins unchanged.  (The
                # trend_enabled gate matters at scale: consulting a
                # cadence/shed-only controller would copy every
                # tenant's flight ring per boundary for nothing.)
                action, trend = self._controller_tenant(record)
                if action == "evict":
                    if self._evict_for_trend(record, trend):
                        continue
                if action in ("restart", "quarantine"):
                    self._unhealthy(
                        bucket,
                        record,
                        report.with_trend(
                            [f"controller trend verdict: {trend.action}"]
                        ),
                    )
                    continue
            if report.healthy:
                if record.segments_since_checkpoint >= (
                    self._tenant_checkpoint_every(record)
                ):
                    self._checkpoint_tenant(
                        record, bucket.pack.lane_state(lane)
                    )
                continue
            self._unhealthy(bucket, record, report)

    def _record_by_uid(self, uid: int) -> TenantRecord:
        return self._tenants_by_uid[uid]

    # -- per-tenant steering overrides ---------------------------------------
    # A journaled daemon ``steer`` record may shadow the service-wide
    # restart budget / checkpoint cadence for ONE tenant (values live in
    # ``record.steer``); every budget/cadence consult goes through these
    # two reads so the override is honored everywhere or nowhere.
    def _tenant_max_restarts(self, record: TenantRecord) -> int:
        return int(record.steer.get("max_restarts", self.max_restarts))

    def _tenant_checkpoint_every(self, record: TenantRecord) -> int:
        return int(record.steer.get("checkpoint_every", self.checkpoint_every))

    def _evict_for_trend(self, record: TenantRecord, trend: Any) -> bool:
        """Act on a controller ``evict`` decision, through the durable
        seam when one is installed: a serving daemon sets
        :attr:`evict_hook` to its own journaled evict so a
        controller-driven eviction is journaled BEFORE the lane surgery
        — an acked eviction must park on daemon restart, never silently
        resume.  A failed hook (journal refused the record) leaves the
        tenant RUNNING with a warning — an eviction that cannot be made
        durable must not happen, and the threshold verdicts still cover
        the lane; returns whether the eviction went through."""
        evict = self.evict_hook if self.evict_hook is not None else self.evict
        try:
            evict(record.spec.tenant_id)
        except Exception as e:  # noqa: BLE001 - never crash the boundary
            self._note(
                record,
                f"controller eviction (trend verdict {trend.action}) "
                f"could not be applied ({type(e).__name__}: {e}); tenant "
                f"stays running on threshold verdicts",
                warn=True,
            )
            return False
        self._note(
            record,
            f"controller evicted (trend verdict {trend.action}); "
            f"resubmit to resume",
            warn=True,
        )
        return True

    def _controller_tenant(
        self, record: TenantRecord
    ) -> tuple[str | None, Any]:
        """Consult the controller for one threshold-healthy tenant:
        ``(action, trend_decision)`` where action is ``"restart"`` /
        ``"quarantine"`` / ``"evict"``, or ``(None, None)`` when no
        trend verdict fired.  Never raises — a missing per-tenant flight
        recorder degrades the controller's trend plane (structured
        warning, threshold verdicts only), and any controller failure is
        swallowed with a warning event (belt and braces over the
        controller's own guards)."""
        rows = None
        if record.flight is not None:
            try:
                rows = record.flight.rows()
            except Exception:  # noqa: BLE001 - detached/broken recorder
                rows = None
        try:
            trend = self.controller.trend_verdict(
                rows,
                generation=record.generations,
                tenant_id=record.spec.tenant_id,
            )
            if trend is None:
                return None, None
            decision = self.controller.tenant_action(
                trend,
                restarts_used=record.restarts,
                max_restarts=self._tenant_max_restarts(record),
                generation=record.generations,
                tenant_id=record.spec.tenant_id,
            )
            return (decision.action if decision is not None else None), trend
        except Exception as e:  # noqa: BLE001 - advisory plane only
            self._event(
                f"controller consult for tenant "
                f"{record.spec.tenant_id!r} failed ({type(e).__name__}: "
                f"{e}); threshold verdicts only",
                warn=True,
                category="control",
                tenant_id=record.spec.tenant_id,
            )
            return None, None

    def _complete(self, bucket: _Bucket, record: TenantRecord) -> None:
        state = bucket.pack.lane_state(record.lane)
        record.status = TenantStatus.COMPLETED
        self._checkpoint_tenant(record, state)
        record.result = jax.device_get(state)
        bucket.pack.release(record.lane)
        record.lane = None
        self.stats.completed += 1
        self._inc(
            "evox_tenant_completed_total",
            "Tenant runs completed.",
            tenant_id=record.spec.tenant_id,
        )
        self._note(
            record,
            f"completed at generation {record.generations} (lane freed)",
        )

    def _unhealthy(
        self, bucket: _Bucket, record: TenantRecord, report: Any
    ) -> None:
        reasons = "; ".join(report.reasons)
        if record.restarts < self._tenant_max_restarts(record):
            resumed = self._resume_state(bucket, record)
            if resumed is not None:
                state, generations = resumed
                record.restarts += 1
                # Same stream discipline as RollbackToCheckpoint: replay
                # from the known-good state with every PRNG leaf folded by
                # the restart index, so the retry explores a fresh
                # trajectory deterministically.
                state = perturb_prng_keys(state, record.restarts)
                mon = bucket.workflow.monitor
                if "monitor" in state:
                    state = state.replace(
                        monitor=mon.record_restart(state["monitor"])
                    )
                bucket.pack.write_lane(record.lane, state)
                record.generations = generations
                # The rollback replays generations the tenant's monitor
                # already recorded: prune the stale tail or the replay's
                # tags would collide (duplicate-tag guard in the history
                # accessors).
                if record.monitor is not None and hasattr(
                    record.monitor, "truncate_history"
                ):
                    record.monitor.truncate_history(generations)
                self.health.reset_lane(record.uid)
                self.stats.restarts += 1
                self._inc(
                    "evox_tenant_restarts_total",
                    "Rollback restarts burned, per tenant.",
                    tenant_id=record.spec.tenant_id,
                )
                self._note(
                    record,
                    f"restart #{record.restarts} (rollback to generation "
                    f"{generations}): {reasons}",
                    warn=True,
                )
                return
        bucket.pack.set_frozen(record.lane, True)
        record.status = TenantStatus.QUARANTINED
        self.stats.quarantines += 1
        self._quarantine_tail(bucket, record, reasons)

    # -- elastic HPO growth (evox_tpu.hpo) -----------------------------------
    def _maybe_grow_hpo(self, bucket: _Bucket, record: TenantRecord) -> bool:
        """Consult the controller's ``hpo-grow`` plane for one healthy HPO
        tenant; apply the bucket re-key + lane surgery when a growth
        fires.  Returns whether the tenant was regrown (the caller then
        skips ordinary boundary handling).  Never raises — any failure
        leaves the tenant running on threshold verdicts with a warning."""
        from ..hpo.elastic import grow_evidence
        from ..hpo.nested import candidate_series, find_nested

        nested = find_nested(record.spec.problem)
        if nested is None:
            return False
        # Growths share the restart budget: a ladder at its budget
        # quarantines like any other degenerating tenant instead of
        # growing without bound.
        if record.restarts + record.grows >= self._tenant_max_restarts(record):
            return False
        try:
            state = bucket.pack.lane_state(record.lane)
            series = candidate_series(
                state["problem"] if "problem" in state else None
            )
            if not series:
                return False
            evidence = grow_evidence(
                record.spec.grow, series, nested.inner_pop
            )
            if evidence is None:
                return False
            decision = self.controller.hpo_grow(
                evidence=evidence,
                generation=record.generations,
                tenant_id=record.spec.tenant_id,
            )
        except Exception as e:  # noqa: BLE001 - never crash the boundary
            self._note(
                record,
                f"hpo-grow consult failed ({type(e).__name__}: {e}); "
                f"tenant continues ungrown",
                warn=True,
            )
            return False
        if decision is None or decision.action in ("", "hold"):
            return False
        return self._grow_hpo(bucket, record, decision, state)

    def _grow_hpo(
        self,
        bucket: _Bucket,
        record: TenantRecord,
        decision: Any,
        state: State,
    ) -> bool:
        """Apply one journaled ``hpo-grow`` decision: regrow the tenant's
        nested problem to the decision's target inner population, re-key
        its bucket (a changed inner pop is a different compiled program),
        and move the tenant's state — outer search state preserved, inner
        instances deterministically rebuilt at the grown size — into the
        new bucket's pack (lane surgery, the PR-8 machinery)."""
        from ..hpo.nested import find_nested

        nested = find_nested(record.spec.problem)
        if record.spec.problem is not nested:
            # Re-keying would have to rebuild the wrapper chain around the
            # grown problem; refuse rather than guess at wrapper state.
            self._note(
                record,
                "hpo-grow decision not applied: the spec's problem wraps "
                "the NestedProblem (growth needs the nested problem as "
                "the spec problem itself)",
                warn=True,
            )
            return False
        new_pop = int(decision.action)
        old_pop = nested.inner_pop
        grown = nested.with_inner_pop(new_pop, record.spec.grow.inner_factory)
        record.grows += 1
        prob_state = grown.regrow_state(
            state["problem"], record.spec.grow.salt + record.grows
        )
        new_state = state.replace(problem=prob_state)
        # Lane surgery: out of the old bucket's pack...
        bucket.pack.release(record.lane)
        record.lane = None
        self._templates.pop((record.bucket, record.uid), None)
        record.spec = dataclasses.replace(record.spec, problem=grown)
        # ... into the grown bucket's (the re-key: a new static signature
        # is a new compilation bucket, created on first use).
        new_bucket = self._bucket_for(record.spec)
        record.bucket = new_bucket.key
        self.health.reset_lane(record.uid)
        self._inc(
            "evox_hpo_grows_total",
            "Elastic inner-population growths applied to HPO tenants.",
            tenant_id=record.spec.tenant_id,
        )
        if new_bucket.pack.free_lanes():
            record.lane = new_bucket.pack.admit(new_state, record.uid)
            # The grown state is the tenant's first resume point at the
            # new shape (older, smaller-shape archives in the namespace
            # are skipped by template validation on any later resume).
            self._checkpoint_tenant(record, new_state)
            self._note(
                record,
                f"hpo-grow #{record.grows}: inner population {old_pop} -> "
                f"{new_pop} (decision #{decision.seq}; bucket re-keyed, "
                f"lane {record.lane})",
                warn=True,
            )
        else:
            self._checkpoint_tenant(record, new_state)
            record.status = TenantStatus.EVICTED
            self._note(
                record,
                f"hpo-grow #{record.grows}: inner population {old_pop} -> "
                f"{new_pop}, but the grown bucket has no free lane — "
                f"parked on the grown checkpoint (resubmit to resume)",
                warn=True,
            )
        return True

    def _quarantine_tail(
        self, bucket: _Bucket, record: TenantRecord, reasons: str
    ) -> None:
        self._inc(
            "evox_tenant_quarantines_total",
            "Lane freezes after a spent restart budget, per tenant.",
            tenant_id=record.spec.tenant_id,
        )
        self._checkpoint_tenant(
            record, bucket.pack.lane_state(record.lane)
        )
        self._note(
            record,
            f"quarantined at generation {record.generations} (lane "
            f"frozen; restart budget "
            f"{record.restarts}/{self._tenant_max_restarts(record)} "
            f"spent): {reasons}",
            warn=True,
        )
