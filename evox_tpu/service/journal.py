"""Crash-safe request journal — the service's durable source of truth.

An :class:`~evox_tpu.service.OptimizationService` is in-memory: a daemon
SIGKILLed between accepting a tenant and that tenant's first checkpoint
forgets the submission ever happened.  The journal closes that hole.
Every externally-visible state transition — submit, readmit, evict,
retire, complete, preempt — is one **atomic, fsync'd, checksummed
record** appended *before* the operation is acknowledged to the caller,
so a restarted daemon reconstructs the exact set of live tenants by
replaying the journal and letting each tenant's checkpoint namespace
supply the values (the PR-8 resume machinery).  The guarantee is
**at-least-once**: a crash can lose at most the one record whose append
had not yet returned (the caller never got an ack for it and must
retry), and replay is idempotent — duplicate records for a uid collapse
onto the newest state.

**Record format** (one JSON object per line, greppable and
``jq``-friendly)::

    {"body": {"seq": 12, "kind": "submit", "at": 1722..., "data": {...}},
     "sha": "<sha256 of the canonical body JSON>"}

``seq`` is strictly increasing; ``sha`` covers the canonically-encoded
body, so a torn append (truncated line), a bit flip anywhere in the
record, or a forged/reordered line all fail validation.  Appends are
``flush`` + ``fsync`` per record (durability is the point; the record
rate is bounded by admission, not by generations), and a failed append
(``ENOSPC``) truncates the file back to the pre-append offset so the
journal never grows an internally-torn middle.

**Replay discipline** (:meth:`RequestJournal.replay`): records are
validated in order; the FIRST invalid record ends the trusted prefix.
Everything from that byte on is the **damaged tail** — it is quarantined
to ``<journal>.corrupt[.N]`` (evidence, never deleted) and the journal
file is truncated back to the last valid record, so subsequent appends
extend a clean prefix.  Because every acknowledged record was fsync'd
before its ack, the damaged tail can only contain unacknowledged (or
post-crash garbage) bytes — the at-most-one-lost-record bound.

Every *mutating* file operation — appends, the repair truncate, the
quarantine-tail write — routes through the
:class:`~evox_tpu.utils.CheckpointStore` seam, so
``resilience.FaultyStore`` injects torn records, bit flips, and
``ENOSPC`` mid-append deterministically (``tests/test_daemon.py``);
replay's read is a plain file read, since damaged bytes are exactly what
it exists to classify.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Union

from ..utils.checkpoint import CheckpointStore, quarantine_target

__all__ = ["RequestJournal", "JournalRecord", "JournalError", "JournalDamage"]


class JournalError(RuntimeError):
    """An append could not be made durable (or the journal has an unhealed
    torn tail).  The operation it guarded must be treated as
    unacknowledged — the caller retries or rejects upstream."""


@dataclass
class JournalRecord:
    """One validated journal record."""

    seq: int
    kind: str
    at: float
    data: dict[str, Any]


@dataclass
class JournalDamage:
    """What :meth:`RequestJournal.replay` found past the trusted prefix."""

    offset: int  # byte offset the trusted prefix ends at
    reason: str  # why the first rejected record failed validation
    bytes_quarantined: int
    quarantine_path: Path | None  # None when the tail could not be saved
    truncated: bool  # whether the journal was cut back to the prefix


def _canonical(body: dict[str, Any]) -> str:
    return json.dumps(body, sort_keys=True, separators=(",", ":"))




class RequestJournal:
    """Append-only, checksummed, fsync-per-record journal.

    :param path: journal file (created on first append).
    :param store: the :class:`~evox_tpu.utils.CheckpointStore` appends,
        truncations, and quarantine writes route through
        (chaos-injectable; a read-only store refuses appends with
        ``EROFS``).
    :param durable: ``fsync`` after every record (default True — an
        un-fsync'd ack is a lie).
    :param registry: optional metrics registry (duck-typed
        :class:`~evox_tpu.obs.MetricsRegistry`): the durability hot path
        publishes ``evox_journal_append_seconds`` /
        ``evox_journal_fsync_seconds`` histograms and an
        ``evox_journal_records_total{kind=}`` counter — the fsync is the
        admission ack's latency floor, and it was unobserved.
        Failure-isolated, same contract as
        ``AsyncCheckpointWriter(registry=)``: a broken registry never
        fails an append.
    """

    def __init__(
        self,
        path: Union[str, Path],
        *,
        store: CheckpointStore | None = None,
        durable: bool = True,
        registry: Any | None = None,
    ):
        self.path = Path(path)
        self.store = store if store is not None else CheckpointStore()
        self.durable = bool(durable)
        self._registry = registry
        self.next_seq = 0
        self.records_appended = 0
        self.append_failures = 0
        self._f: Any | None = None
        # Set when a failed append left bytes we could not truncate away:
        # appending onto an unhealed torn middle would corrupt the clean
        # prefix, so the journal refuses until replay() repairs the file.
        self._dirty = False

    # -- append -------------------------------------------------------------
    def _open(self) -> Any:
        if self._f is None:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._f = self.store.open_append(self.path)
            if self.durable:
                # A freshly-created journal's DIRECTORY ENTRY must survive
                # power loss too: fsyncing the file alone persists data
                # blocks a crashed filesystem may never link — replay
                # would find no journal and every acked tenant would
                # silently vanish.  Failure propagates: the caller's
                # append is then unacknowledged, same as any append fault.
                self.store.fsync_dir(self.path.parent)
        return self._f

    def append(self, kind: str, **data: Any) -> int:
        """Durably append one record; returns its ``seq``.  Raises
        :class:`JournalError` when the record could not be made durable —
        the caller must NOT ack the operation it guards."""
        if self._dirty:
            raise JournalError(
                f"journal {self.path} has an unhealed torn tail from a "
                f"failed append; replay() repairs it"
            )
        body = {
            "seq": self.next_seq,
            "kind": str(kind),
            "at": time.time(),
            "data": data,
        }
        body_json = _canonical(body)
        sha = hashlib.sha256(body_json.encode()).hexdigest()
        line = (
            '{"body":' + body_json + ',"sha":"' + sha + '"}\n'
        ).encode()
        try:
            f = self._open()
        except OSError as e:
            # A read-only store (non-primary fleet process) or a vanished
            # directory: the operation is unacknowledged either way.
            self.append_failures += 1
            raise JournalError(
                f"journal {self.path} could not be opened for append "
                f"({type(e).__name__}: {e}); the operation is "
                f"unacknowledged"
            ) from e
        offset = f.tell()
        t0 = time.perf_counter()
        fsync_seconds = 0.0
        try:
            written = self.store.append_record(f, line)
            f.flush()
            if self.durable:
                t_sync = time.perf_counter()
                os.fsync(f.fileno())
                fsync_seconds = time.perf_counter() - t_sync
        except (OSError, RuntimeError) as e:
            self.append_failures += 1
            self._heal(f, offset)
            raise JournalError(
                f"journal append of {kind!r} record failed "
                f"({type(e).__name__}: {e}); the operation is "
                f"unacknowledged"
            ) from e
        if written != len(line):
            # A store that silently wrote a short record (a lying disk):
            # the on-disk tail is torn.  Cut it back — acking a torn
            # record would break the at-most-one-lost-record bound.
            self.append_failures += 1
            self._heal(f, offset)
            raise JournalError(
                f"journal append of {kind!r} record was torn "
                f"({written}/{len(line)} bytes); the operation is "
                f"unacknowledged"
            )
        self.next_seq += 1
        self.records_appended += 1
        self._observe(kind, time.perf_counter() - t0, fsync_seconds)
        return body["seq"]

    def _observe(
        self, kind: str, append_seconds: float, fsync_seconds: float
    ) -> None:
        """Registry feed, failure-isolated (the AsyncCheckpointWriter
        contract): the durability hot path must never fail on account of
        its own observation."""
        if self._registry is None:
            return
        try:
            self._registry.histogram(
                "evox_journal_append_seconds",
                "Wall seconds per durable journal append (write + flush "
                "+ fsync) — the admission ack's latency floor.",
            ).observe(append_seconds)
            self._registry.histogram(
                "evox_journal_fsync_seconds",
                "Wall seconds of the fsync alone within each append.",
            ).observe(fsync_seconds)
            self._registry.counter(
                "evox_journal_records_total",
                "Journal records durably appended, by record kind.",
                kind=str(kind),
            ).inc()
        except Exception:  # pragma: no cover - broken registry
            pass

    def _heal(self, f: Any, offset: int) -> None:
        """Cut a failed append's partial bytes back off.  If even that
        fails (the disk is gone), poison the journal: future appends
        refuse instead of extending garbage."""
        try:
            f.flush()
        except OSError:
            pass
        try:
            os.ftruncate(f.fileno(), offset)
        except OSError:
            self._dirty = True

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            finally:
                self._f = None

    # -- replay -------------------------------------------------------------
    def replay(
        self, *, quarantine: bool = True
    ) -> tuple[list[JournalRecord], JournalDamage | None]:
        """Validate the journal and return ``(records, damage)``.

        ``records`` is the trusted prefix — every record whose checksum
        and sequence check out, in order.  On the first invalid record the
        rest of the file is the damaged tail: with ``quarantine=True`` it
        is saved to ``<journal>.corrupt[.N]`` and the journal is truncated
        back to the trusted prefix (both route through the store; a
        read-only store leaves the file untouched and only reports).
        ``damage`` is ``None`` for a clean journal.  Also primes
        ``next_seq`` so subsequent appends continue the sequence."""
        self.close()
        try:
            raw = self.path.read_bytes()
        except FileNotFoundError:
            self.next_seq = 0
            return [], None
        records: list[JournalRecord] = []
        offset = 0
        reason: str | None = None
        expected_seq = 0
        while offset < len(raw):
            nl = raw.find(b"\n", offset)
            if nl < 0:
                reason = "truncated record (no terminating newline)"
                break
            line = raw[offset : nl + 1]
            try:
                obj = json.loads(line)
                body = obj["body"]
                sha = obj["sha"]
            except (
                json.JSONDecodeError,
                UnicodeDecodeError,
                KeyError,
                TypeError,
            ) as e:
                reason = f"unparseable record ({type(e).__name__})"
                break
            actual = hashlib.sha256(_canonical(body).encode()).hexdigest()
            if actual != sha:
                reason = (
                    f"checksum mismatch (recorded {str(sha)[:12]}…, "
                    f"recomputed {actual[:12]}…) — bit flip or tamper"
                )
                break
            try:
                seq = int(body["seq"])
                kind = str(body["kind"])
                at = float(body.get("at", 0.0))
                data = dict(body.get("data") or {})
            except (KeyError, TypeError, ValueError) as e:
                reason = f"malformed record body ({type(e).__name__})"
                break
            if seq != expected_seq:
                reason = (
                    f"sequence break (expected seq {expected_seq}, "
                    f"found {seq}) — reordered or spliced records"
                )
                break
            records.append(JournalRecord(seq=seq, kind=kind, at=at, data=data))
            expected_seq = seq + 1
            offset = nl + 1
        self.next_seq = expected_seq
        if reason is None:
            self._dirty = False
            return records, None
        tail = raw[offset:]
        qpath: Path | None = None
        truncated = False
        if quarantine:
            qpath = self._quarantine_tail(tail)
            try:
                self.store.truncate(self.path, offset)
                truncated = True
            except OSError:
                pass
        # Appends may only resume once the damaged tail is actually gone:
        # with quarantine=False (or a failed truncate — read-only store,
        # vanished file) an append would extend the garbage and the NEXT
        # replay would cut the acked record away with it, breaking the
        # at-most-one-lost-record bound.
        self._dirty = not truncated
        return records, JournalDamage(
            offset=offset,
            reason=reason,
            bytes_quarantined=len(tail),
            quarantine_path=qpath,
            truncated=truncated,
        )

    def _quarantine_tail(self, tail: bytes) -> Path | None:
        """Save the damaged tail as evidence (atomic, via the store);
        failure to save must not block the repair — report ``None``."""
        target = quarantine_target(self.path)
        try:
            fd, tmp = self.store.open_temp(
                self.path.parent, target.name + ".tmp."
            )
            try:
                with os.fdopen(fd, "wb") as f:
                    self.store.write_bytes(f, tail)
                self.store.publish(tmp, target)
            except BaseException:
                try:
                    self.store.unlink(tmp)
                except OSError:
                    pass
                raise
        except (OSError, RuntimeError):
            return None
        return target
