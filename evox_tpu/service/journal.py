"""Crash-safe request journal — the service's durable source of truth.

An :class:`~evox_tpu.service.OptimizationService` is in-memory: a daemon
SIGKILLed between accepting a tenant and that tenant's first checkpoint
forgets the submission ever happened.  The journal closes that hole.
Every externally-visible state transition — submit, readmit, evict,
retire, complete, preempt — is one **atomic, fsync'd, checksummed
record** appended *before* the operation is acknowledged to the caller,
so a restarted daemon reconstructs the exact set of live tenants by
replaying the journal and letting each tenant's checkpoint namespace
supply the values (the PR-8 resume machinery).  The guarantee is
**at-least-once**: a crash can lose at most the one record whose append
had not yet returned (the caller never got an ack for it and must
retry), and replay is idempotent — duplicate records for a uid collapse
onto the newest state.

**Record format** (one JSON object per line, greppable and
``jq``-friendly)::

    {"body": {"seq": 12, "kind": "submit", "at": 1722..., "data": {...}},
     "sha": "<sha256 of the canonical body JSON>"}

``seq`` is strictly increasing; ``sha`` covers the canonically-encoded
body, so a torn append (truncated line), a bit flip anywhere in the
record, or a forged/reordered line all fail validation.  Appends are
``flush`` + ``fsync`` per record (durability is the point; the record
rate is bounded by admission, not by generations), and a failed append
(``ENOSPC``) truncates the file back to the pre-append offset so the
journal never grows an internally-torn middle.

**Replay discipline** (:meth:`RequestJournal.replay`): records are
validated in order; the FIRST invalid record ends the trusted prefix.
Everything from that byte on is the **damaged tail** — it is quarantined
to ``<journal>.corrupt[.N]`` (evidence, never deleted) and the journal
file is truncated back to the last valid record, so subsequent appends
extend a clean prefix.  Because every acknowledged record was fsync'd
before its ack, the damaged tail can only contain unacknowledged (or
post-crash garbage) bytes — the at-most-one-lost-record bound.

Every *mutating* file operation — appends, the repair truncate, the
quarantine-tail write — routes through the
:class:`~evox_tpu.utils.CheckpointStore` seam, so
``resilience.FaultyStore`` injects torn records, bit flips, and
``ENOSPC`` mid-append deterministically (``tests/test_daemon.py``);
replay's read is a plain file read, since damaged bytes are exactly what
it exists to classify.

**Compaction** (:meth:`RequestJournal.compact`): an append-only journal
makes restart cost scale with process *lifetime*, not *live state*.
Compaction folds the whole history into one schema-versioned,
checksummed **snapshot** (canonical sorted-key JSON, same envelope as a
record) and swaps in a one-record journal whose ``snapshot-anchor``
record binds the snapshot by name + sha.  The protocol is ordered so
that every crash point leaves a recoverable disk state:

1. publish ``<stem>.snapshot.<seq>`` (temp → fsync → rename → dir
   fsync);
2. publish ``<journal>.compacted.<seq>`` — a byte-for-byte quarantined
   copy of the full pre-compaction journal (the loud fallback);
3. atomically publish the anchored one-record journal over the journal
   path (the swap);
4. only then GC superseded snapshots/copies — and even then the prior
   anchor's snapshot is retained, because the fresh fallback copy's own
   first record still references it (the PR-5 never-delete-before-the-
   successor-is-durable discipline).

Replay loads the anchor's snapshot as the base state and folds the
suffix records onto it.  A torn / bit-flipped / missing snapshot — or a
torn swap that destroyed the anchor itself — falls back **loudly**
(``replay_notes`` + a warning) to the quarantined full-journal copy;
only when both the snapshot and its fallback are unusable does replay
raise, because proceeding would silently drop acknowledged records.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import warnings
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Union

from ..utils.checkpoint import CheckpointStore, quarantine_target

__all__ = [
    "RequestJournal",
    "JournalRecord",
    "JournalError",
    "JournalDamage",
    "JournalSnapshot",
    "CompactionResult",
    "SNAPSHOT_SCHEMA",
    "ANCHOR_KIND",
]

#: Snapshot payload schema version.  Replay refuses snapshots from a
#: schema it does not understand (falling back to the quarantined full
#: journal) instead of guessing at field meanings.
SNAPSHOT_SCHEMA = 1

#: Record kind of the compaction anchor — the only record kind with
#: journal-level meaning; every other kind is opaque caller payload.
ANCHOR_KIND = "snapshot-anchor"


class JournalError(RuntimeError):
    """An append could not be made durable (or the journal has an unhealed
    torn tail).  The operation it guarded must be treated as
    unacknowledged — the caller retries or rejects upstream."""


@dataclass
class JournalRecord:
    """One validated journal record."""

    seq: int
    kind: str
    at: float
    data: dict[str, Any]


@dataclass
class JournalDamage:
    """What :meth:`RequestJournal.replay` found past the trusted prefix."""

    offset: int  # byte offset the trusted prefix ends at
    reason: str  # why the first rejected record failed validation
    bytes_quarantined: int
    quarantine_path: Path | None  # None when the tail could not be saved
    truncated: bool  # whether the journal was cut back to the prefix


@dataclass
class JournalSnapshot:
    """A validated, loaded journal snapshot: the folded state of every
    record up to and including ``seq - 1``, anchored at ``seq``."""

    seq: int  # the anchor record's seq (first live suffix seq is seq+1)
    at: float  # wall time the snapshot was taken
    schema: int
    state: dict[str, Any]  # caller-defined folded state
    path: Path  # the snapshot file the anchor bound


@dataclass
class CompactionResult:
    """What one successful :meth:`RequestJournal.compact` did."""

    seq: int
    snapshot_path: Path
    fallback_path: Path  # quarantined full pre-compaction journal
    folded_records: int  # suffix records folded into the snapshot
    bytes_before: int
    bytes_after: int
    removed: list[str] = field(default_factory=list)  # GC'd predecessors


def _canonical(body: dict[str, Any]) -> str:
    return json.dumps(body, sort_keys=True, separators=(",", ":"))




class RequestJournal:
    """Append-only, checksummed, fsync-per-record journal.

    :param path: journal file (created on first append).
    :param store: the :class:`~evox_tpu.utils.CheckpointStore` appends,
        truncations, and quarantine writes route through
        (chaos-injectable; a read-only store refuses appends with
        ``EROFS``).
    :param durable: ``fsync`` after every record (default True — an
        un-fsync'd ack is a lie).
    :param registry: optional metrics registry (duck-typed
        :class:`~evox_tpu.obs.MetricsRegistry`): the durability hot path
        publishes ``evox_journal_append_seconds`` /
        ``evox_journal_fsync_seconds`` histograms and an
        ``evox_journal_records_total{kind=}`` counter — the fsync is the
        admission ack's latency floor, and it was unobserved.
        Failure-isolated, same contract as
        ``AsyncCheckpointWriter(registry=)``: a broken registry never
        fails an append.
    """

    def __init__(
        self,
        path: Union[str, Path],
        *,
        store: CheckpointStore | None = None,
        durable: bool = True,
        registry: Any | None = None,
    ):
        self.path = Path(path)
        self.store = store if store is not None else CheckpointStore()
        self.durable = bool(durable)
        self._registry = registry
        self.next_seq = 0
        self.records_appended = 0
        self.append_failures = 0
        self._f: Any | None = None
        # Set when a failed append left bytes we could not truncate away:
        # appending onto an unhealed torn middle would corrupt the clean
        # prefix, so the journal refuses until replay() repairs the file.
        self._dirty = False
        # Compaction state, primed by replay()/compact().
        self.snapshot: JournalSnapshot | None = None
        self.compactions = 0
        self.snapshot_fallbacks = 0
        # Every snapshot/fallback file the last replay's base chain
        # actually used — compaction's GC keep-set, so reaping can never
        # sever the recovery chain the current journal depends on.
        self._base_refs: set[str] = set()
        # Human-readable recovery anomalies from the last replay()
        # (snapshot fallback, gap warnings) — the caller's loudness
        # channel; the daemon surfaces each as a warning event.
        self.replay_notes: list[str] = []

    # -- snapshot accessors --------------------------------------------------
    @property
    def snapshot_state(self) -> dict[str, Any] | None:
        return None if self.snapshot is None else self.snapshot.state

    @property
    def snapshot_seq(self) -> int | None:
        return None if self.snapshot is None else self.snapshot.seq

    @property
    def snapshot_at(self) -> float | None:
        return None if self.snapshot is None else self.snapshot.at

    @property
    def records_since_snapshot(self) -> int:
        """Suffix records replay must fold on a cold start — the number
        compaction would collapse into the next snapshot."""
        if self.snapshot is None:
            return self.next_seq
        return max(0, self.next_seq - self.snapshot.seq - 1)

    @property
    def size_bytes(self) -> int:
        try:
            return int(self.path.stat().st_size)
        except OSError:
            return 0

    # -- append -------------------------------------------------------------
    def _open(self) -> Any:
        if self._f is None:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._f = self.store.open_append(self.path)
            if self.durable:
                # A freshly-created journal's DIRECTORY ENTRY must survive
                # power loss too: fsyncing the file alone persists data
                # blocks a crashed filesystem may never link — replay
                # would find no journal and every acked tenant would
                # silently vanish.  Failure propagates: the caller's
                # append is then unacknowledged, same as any append fault.
                self.store.fsync_dir(self.path.parent)
        return self._f

    def append(self, kind: str, **data: Any) -> int:
        """Durably append one record; returns its ``seq``.  Raises
        :class:`JournalError` when the record could not be made durable —
        the caller must NOT ack the operation it guards."""
        if self._dirty:
            raise JournalError(
                f"journal {self.path} has an unhealed torn tail from a "
                f"failed append; replay() repairs it"
            )
        body = {
            "seq": self.next_seq,
            "kind": str(kind),
            "at": time.time(),
            "data": data,
        }
        line = self._encode_record(body)
        try:
            f = self._open()
        except OSError as e:
            # A read-only store (non-primary fleet process) or a vanished
            # directory: the operation is unacknowledged either way.
            self.append_failures += 1
            raise JournalError(
                f"journal {self.path} could not be opened for append "
                f"({type(e).__name__}: {e}); the operation is "
                f"unacknowledged"
            ) from e
        offset = f.tell()
        t0 = time.perf_counter()
        fsync_seconds = 0.0
        try:
            written = self.store.append_record(f, line)
            f.flush()
            if self.durable:
                t_sync = time.perf_counter()
                os.fsync(f.fileno())
                fsync_seconds = time.perf_counter() - t_sync
        except (OSError, RuntimeError) as e:
            self.append_failures += 1
            self._heal(f, offset)
            raise JournalError(
                f"journal append of {kind!r} record failed "
                f"({type(e).__name__}: {e}); the operation is "
                f"unacknowledged"
            ) from e
        if written != len(line):
            # A store that silently wrote a short record (a lying disk):
            # the on-disk tail is torn.  Cut it back — acking a torn
            # record would break the at-most-one-lost-record bound.
            self.append_failures += 1
            self._heal(f, offset)
            raise JournalError(
                f"journal append of {kind!r} record was torn "
                f"({written}/{len(line)} bytes); the operation is "
                f"unacknowledged"
            )
        self.next_seq += 1
        self.records_appended += 1
        self._observe(kind, time.perf_counter() - t0, fsync_seconds)
        return body["seq"]

    @staticmethod
    def _encode_record(body: dict[str, Any]) -> bytes:
        """One wire-format journal line: canonical body + its sha, in a
        fixed envelope so the sha always covers exactly the body bytes
        replay will recompute over."""
        body_json = _canonical(body)
        sha = hashlib.sha256(body_json.encode()).hexdigest()
        return ('{"body":' + body_json + ',"sha":"' + sha + '"}\n').encode()

    def _observe(
        self, kind: str, append_seconds: float, fsync_seconds: float
    ) -> None:
        """Registry feed, failure-isolated (the AsyncCheckpointWriter
        contract): the durability hot path must never fail on account of
        its own observation."""
        if self._registry is None:
            return
        try:
            self._registry.histogram(
                "evox_journal_append_seconds",
                "Wall seconds per durable journal append (write + flush "
                "+ fsync) — the admission ack's latency floor.",
            ).observe(append_seconds)
            self._registry.histogram(
                "evox_journal_fsync_seconds",
                "Wall seconds of the fsync alone within each append.",
            ).observe(fsync_seconds)
            self._registry.counter(
                "evox_journal_records_total",
                "Journal records durably appended, by record kind.",
                kind=str(kind),
            ).inc()
        except Exception:  # pragma: no cover - broken registry
            pass

    def _heal(self, f: Any, offset: int) -> None:
        """Cut a failed append's partial bytes back off.  If even that
        fails (the disk is gone), poison the journal: future appends
        refuse instead of extending garbage."""
        try:
            f.flush()
        except OSError:
            pass
        try:
            os.ftruncate(f.fileno(), offset)
        except OSError:
            self._dirty = True

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            finally:
                self._f = None

    # -- replay -------------------------------------------------------------
    def replay(
        self, *, quarantine: bool = True
    ) -> tuple[list[JournalRecord], JournalDamage | None]:
        """Validate the journal and return ``(records, damage)``.

        ``records`` is the trusted prefix — every record whose checksum
        and sequence check out, in order.  On the first invalid record the
        rest of the file is the damaged tail: with ``quarantine=True`` it
        is saved to ``<journal>.corrupt[.N]`` and the journal is truncated
        back to the trusted prefix (both route through the store; a
        read-only store leaves the file untouched and only reports).
        ``damage`` is ``None`` for a clean journal.  Also primes
        ``next_seq`` so subsequent appends continue the sequence.

        A journal whose first record is a ``snapshot-anchor`` loads the
        referenced snapshot into :attr:`snapshot` and returns only the
        suffix records — the caller folds the suffix onto
        :attr:`snapshot_state`.  An unusable snapshot (torn, flipped,
        missing, wrong schema) falls back loudly to the quarantined
        pre-compaction journal copy named in the anchor; a destroyed
        anchor (torn swap) restores the journal from the newest
        quarantined copy.  Only when every fallback is exhausted does
        replay raise :class:`JournalError` — acknowledged records are
        never dropped silently."""
        self.close()
        self.replay_notes = []
        self.snapshot = None
        self._base_refs = set()
        try:
            raw = self.path.read_bytes()
        except FileNotFoundError:
            self.next_seq = 0
            return [], None
        anchor, records, reason, offset, next_seq = self._scan(raw)
        if anchor is None and not records and reason is not None:
            # Record 0 itself is damaged.  A torn or bit-flipped
            # compaction swap does exactly this — before declaring total
            # loss, recover from the newest quarantined pre-compaction
            # copy (the step-2 artifact, published before the swap).
            restored = self._restore_from_fallback(
                raw, reason, quarantine=quarantine
            )
            if restored is not None:
                return restored
        if anchor is not None:
            base, err = self._anchor_base(anchor)
            if err is not None:
                raise JournalError(err)
            records = base + records
        self.next_seq = next_seq
        if reason is None:
            self._dirty = False
            return records, None
        tail = raw[offset:]
        qpath: Path | None = None
        truncated = False
        if quarantine:
            qpath = self._quarantine_tail(tail)
            try:
                self.store.truncate(self.path, offset)
                truncated = True
            except OSError:
                pass
        # Appends may only resume once the damaged tail is actually gone:
        # with quarantine=False (or a failed truncate — read-only store,
        # vanished file) an append would extend the garbage and the NEXT
        # replay would cut the acked record away with it, breaking the
        # at-most-one-lost-record bound.
        self._dirty = not truncated
        return records, JournalDamage(
            offset=offset,
            reason=reason,
            bytes_quarantined=len(tail),
            quarantine_path=qpath,
            truncated=truncated,
        )

    def _scan(
        self, raw: bytes
    ) -> tuple[
        JournalRecord | None, list[JournalRecord], str | None, int, int
    ]:
        """Validate one journal byte stream.  Returns ``(anchor,
        records, reason, offset, next_seq)``: the leading
        ``snapshot-anchor`` record when present (never included in
        ``records``), the trusted records after it, the first validation
        failure (``None`` when clean), the byte offset the trusted
        prefix ends at, and the seq the next append would take."""
        anchor: JournalRecord | None = None
        records: list[JournalRecord] = []
        offset = 0
        reason: str | None = None
        expected_seq = 0
        while offset < len(raw):
            nl = raw.find(b"\n", offset)
            if nl < 0:
                reason = "truncated record (no terminating newline)"
                break
            line = raw[offset : nl + 1]
            try:
                obj = json.loads(line)
                body = obj["body"]
                sha = obj["sha"]
            except (
                json.JSONDecodeError,
                UnicodeDecodeError,
                KeyError,
                TypeError,
            ) as e:
                reason = f"unparseable record ({type(e).__name__})"
                break
            actual = hashlib.sha256(_canonical(body).encode()).hexdigest()
            if actual != sha:
                reason = (
                    f"checksum mismatch (recorded {str(sha)[:12]}…, "
                    f"recomputed {actual[:12]}…) — bit flip or tamper"
                )
                break
            try:
                seq = int(body["seq"])
                kind = str(body["kind"])
                at = float(body.get("at", 0.0))
                data = dict(body.get("data") or {})
            except (KeyError, TypeError, ValueError) as e:
                reason = f"malformed record body ({type(e).__name__})"
                break
            if kind == ANCHOR_KIND:
                # The anchor seeds the sequence: it consumed the seq the
                # compaction observed, so the suffix continues from
                # seq + 1.  Anywhere but record 0 it is spliced damage.
                if offset != 0:
                    reason = (
                        "snapshot-anchor out of position (not record 0) "
                        "— spliced or replayed compaction record"
                    )
                    break
                if not str(data.get("snapshot") or ""):
                    reason = "snapshot-anchor carries no snapshot name"
                    break
                anchor = JournalRecord(seq=seq, kind=kind, at=at, data=data)
                expected_seq = seq + 1
                offset = nl + 1
                continue
            if seq != expected_seq:
                reason = (
                    f"sequence break (expected seq {expected_seq}, "
                    f"found {seq}) — reordered or spliced records"
                )
                break
            records.append(JournalRecord(seq=seq, kind=kind, at=at, data=data))
            expected_seq = seq + 1
            offset = nl + 1
        return anchor, records, reason, offset, expected_seq

    def _note(self, message: str) -> None:
        """The loudness channel: recovery anomalies are recorded for the
        caller (the daemon turns each into a warning event) and warned,
        never swallowed."""
        self.replay_notes.append(message)
        warnings.warn(f"journal {self.path.name}: {message}", RuntimeWarning)

    def _load_snapshot(self, anchor: JournalRecord) -> None:
        """Load and validate the snapshot an anchor binds; raises
        :class:`JournalError` on any mismatch (the caller falls back)."""
        name = str(anchor.data.get("snapshot") or "")
        spath = self.path.parent / name
        try:
            sraw = spath.read_bytes()
        except OSError as e:
            raise JournalError(
                f"snapshot {name!r} unreadable ({type(e).__name__}: {e})"
            ) from e
        try:
            obj = json.loads(sraw)
            body = obj["body"]
            sha = obj["sha"]
        except (
            json.JSONDecodeError,
            UnicodeDecodeError,
            KeyError,
            TypeError,
        ) as e:
            raise JournalError(
                f"snapshot {name!r} unparseable ({type(e).__name__}) — "
                f"torn write"
            ) from e
        actual = hashlib.sha256(_canonical(body).encode()).hexdigest()
        if actual != sha:
            raise JournalError(
                f"snapshot {name!r} checksum mismatch — bit flip or torn "
                f"write"
            )
        if str(anchor.data.get("sha") or "") != str(sha):
            raise JournalError(
                f"snapshot {name!r} does not match its anchor's sha "
                f"binding — stale or swapped snapshot file"
            )
        try:
            schema = int(body.get("schema", -1))
            seq = int(body.get("seq", -1))
            at = float(body.get("at", 0.0))
            state = dict(body.get("state") or {})
        except (TypeError, ValueError) as e:
            raise JournalError(
                f"snapshot {name!r} malformed body ({type(e).__name__})"
            ) from e
        if schema != SNAPSHOT_SCHEMA:
            raise JournalError(
                f"snapshot {name!r} schema {schema} unsupported "
                f"(this build understands {SNAPSHOT_SCHEMA})"
            )
        if seq != anchor.seq:
            raise JournalError(
                f"snapshot {name!r} is anchored at seq {seq}, anchor "
                f"says {anchor.seq}"
            )
        self.snapshot = JournalSnapshot(
            seq=seq, at=at, schema=schema, state=state, path=spath
        )
        self._base_refs.add(name)

    def _anchor_base(
        self, anchor: JournalRecord, depth: int = 0
    ) -> tuple[list[JournalRecord], str | None]:
        """The base state an anchor stands for.  Primary: its snapshot
        (loaded into :attr:`snapshot`, base records empty).  Fallback:
        the quarantined full-journal copy the anchor names — loud, and
        recursive when that copy begins with an older anchor.  Returns
        ``(base_records, error)``; ``error`` is a refusal (acked records
        would be silently lost) when every source is unusable."""
        if depth > 8:
            return [], (
                "compaction fallback chain deeper than 8 — refusing "
                "(corrupt or cyclic anchor references)"
            )
        fallback = str(anchor.data.get("fallback") or "")
        try:
            self._load_snapshot(anchor)
            return [], None
        except JournalError as e:
            self.snapshot_fallbacks += 1
            self._note(
                f"snapshot for anchor seq {anchor.seq} is unusable ({e}); "
                f"falling back to quarantined full journal {fallback!r}"
            )
        if not fallback:
            return [], (
                f"snapshot for anchor seq {anchor.seq} is unusable and "
                f"the anchor records no fallback copy; refusing to "
                f"silently drop acked records"
            )
        src = self.path.parent / fallback
        try:
            fraw = src.read_bytes()
        except OSError as e:
            return [], (
                f"snapshot for anchor seq {anchor.seq} is unusable and "
                f"its fallback {fallback!r} is unreadable "
                f"({type(e).__name__}: {e}); refusing to silently drop "
                f"acked records"
            )
        self._base_refs.add(fallback)
        fanchor, frecords, freason, _foffset, fnext = self._scan(fraw)
        if freason is not None:
            self._note(
                f"fallback journal {fallback!r} has a damaged tail "
                f"({freason}); folding its trusted prefix"
            )
        base: list[JournalRecord] = []
        if fanchor is not None:
            base, err = self._anchor_base(fanchor, depth + 1)
            if err is not None:
                return [], err
        if fnext != anchor.seq:
            self._note(
                f"fallback journal {fallback!r} ends at seq {fnext - 1} "
                f"but the anchor expects seq {anchor.seq - 1}; records "
                f"in the gap are lost — inspect the quarantine files"
            )
        return base + frecords, None

    def _restore_from_fallback(
        self, raw: bytes, reason: str, *, quarantine: bool
    ) -> tuple[list[JournalRecord], JournalDamage | None] | None:
        """Record 0 of the journal is damaged (the signature of a torn
        or bit-flipped compaction swap): quarantine the wreck and
        restore the journal from the newest ``<journal>.compacted.<seq>``
        copy.  Returns the full replay result, or ``None`` when no copy
        exists (the caller reports ordinary damage)."""
        candidates = sorted(
            self.path.parent.glob(self.path.name + ".compacted.*")
        )
        candidates = [c for c in candidates if ".tmp." not in c.name]
        if not candidates:
            return None
        src = candidates[-1]
        try:
            fraw = src.read_bytes()
        except OSError:
            return None
        self.snapshot_fallbacks += 1
        self._note(
            f"record 0 is damaged ({reason}) — the signature of a torn "
            f"compaction swap; restoring from quarantined copy {src.name}"
        )
        qpath: Path | None = None
        restored = False
        if quarantine:
            qpath = self._quarantine_tail(raw)
            try:
                self._publish_bytes(fraw, self.path)
                restored = True
            except (OSError, RuntimeError) as e:
                self._note(
                    f"could not restore the journal from {src.name} "
                    f"({type(e).__name__}: {e}); replaying the copy "
                    f"read-only"
                )
        self._dirty = not restored
        fanchor, records, freason, foffset, fnext = self._scan(fraw)
        if fanchor is not None:
            base, err = self._anchor_base(fanchor)
            if err is not None:
                raise JournalError(err)
            records = base + records
        self.next_seq = fnext
        if freason is not None:
            self._note(
                f"quarantined copy {src.name} has a damaged tail "
                f"({freason}); using its trusted prefix"
            )
            if restored:
                try:
                    self.store.truncate(self.path, foffset)
                except OSError:
                    self._dirty = True
        return records, JournalDamage(
            offset=0,
            reason=f"{reason}; recovered from {src.name}",
            bytes_quarantined=len(raw),
            quarantine_path=qpath,
            truncated=restored,
        )

    def _quarantine_tail(self, tail: bytes) -> Path | None:
        """Save the damaged tail as evidence (atomic, via the store);
        failure to save must not block the repair — report ``None``."""
        target = quarantine_target(self.path)
        try:
            self._publish_bytes(tail, target)
        except (OSError, RuntimeError):
            return None
        return target

    # -- compaction ---------------------------------------------------------
    def _publish_bytes(self, data: bytes, final: Path) -> None:
        """Atomically publish ``data`` at ``final`` through the store:
        same-directory temp → write → fsync → rename → directory fsync.
        Any fault raises with the previous ``final`` intact (the rename
        is the commit point) and the temp unlinked."""
        fd, tmp = self.store.open_temp(final.parent, final.name + ".tmp.")
        try:
            with os.fdopen(fd, "wb") as f:
                self.store.write_bytes(f, data)
                f.flush()
                if self.durable:
                    self.store.fsync_file(f)
            self.store.publish(tmp, final)
        except BaseException:
            try:
                self.store.unlink(tmp)
            except OSError:
                pass
            raise
        if self.durable:
            self.store.fsync_dir(final.parent)

    def compact(
        self,
        fold: Callable[[dict[str, Any] | None, list[JournalRecord]], dict],
    ) -> CompactionResult:
        """Fold the whole journal into one snapshot and swap in a
        one-record anchored journal.  ``fold(base_state, records)``
        must be a pure function of the prior snapshot state (``None``
        before the first compaction) and the suffix records — the exact
        fold replay uses, so a compacted cold start is bit-for-bit the
        state a full replay would build.

        Ordering is the crash-safety argument (see the module
        docstring): snapshot first, full-journal quarantine copy second,
        the atomic swap third, GC last — a kill between any two steps
        leaves either the old journal intact or a swap whose anchor can
        reach a durable base.  Raises :class:`JournalError` on any
        fault, with the journal still valid (the swap's rename is the
        only commit point)."""
        records, _damage = self.replay(quarantine=True)
        if self._dirty:
            raise JournalError(
                f"journal {self.path} has an unhealed damaged tail; "
                f"compaction refused until replay can repair it"
            )
        base = self.snapshot_state
        # Everything the base chain the replay just walked still needs:
        # the prior snapshot in the healthy case, or the fallback
        # copies (recursively) when a snapshot was unusable.  The fresh
        # full-journal copy's record 0 keeps referencing that chain, so
        # GC below must not sever it.
        base_refs = set(self._base_refs)
        if not records and base is None:
            raise JournalError("nothing to compact (empty journal)")
        seq = self.next_seq
        at = time.time()
        state = fold(base, records)
        body = {
            "schema": SNAPSHOT_SCHEMA,
            "seq": seq,
            "at": at,
            "state": state,
        }
        try:
            body_json = _canonical(body)
        except (TypeError, ValueError) as e:
            raise JournalError(
                f"snapshot state is not canonically JSON-serializable "
                f"({type(e).__name__}: {e})"
            ) from e
        sha = hashlib.sha256(body_json.encode()).hexdigest()
        snap_bytes = (
            '{"body":' + body_json + ',"sha":"' + sha + '"}\n'
        ).encode()
        snap_name = f"{self.path.stem}.snapshot.{seq:08d}"
        fallback_name = f"{self.path.name}.compacted.{seq:08d}"
        try:
            raw = self.path.read_bytes()
        except FileNotFoundError:
            raw = b""
        anchor_line = self._encode_record(
            {
                "seq": seq,
                "kind": ANCHOR_KIND,
                "at": at,
                "data": {
                    "snapshot": snap_name,
                    "sha": sha,
                    "schema": SNAPSHOT_SCHEMA,
                    "fallback": fallback_name,
                    "folded": len(records),
                },
            }
        )
        try:
            # Step 1 — the snapshot, durable before anything references
            # it.
            self._publish_bytes(snap_bytes, self.path.parent / snap_name)
            # Step 2 — quarantine the FULL pre-compaction journal.  From
            # here on there is no instant without a complete readable
            # history on disk: if the snapshot later turns out torn,
            # replay falls back to this copy.
            self._publish_bytes(raw, self.path.parent / fallback_name)
            # Step 3 — the swap: one rename replaces the journal with a
            # single anchor record binding the snapshot by name + sha.
            self._publish_bytes(anchor_line, self.path)
        except (OSError, RuntimeError) as e:
            # Orphaned step-1/2 artifacts are GC'd by the next
            # successful compaction; the journal itself is unchanged.
            raise JournalError(
                f"compaction at seq {seq} failed "
                f"({type(e).__name__}: {e}); serving continues on the "
                f"uncompacted journal"
            ) from e
        self.snapshot = JournalSnapshot(
            seq=seq,
            at=at,
            schema=SNAPSHOT_SCHEMA,
            state=state if isinstance(state, dict) else dict(state),
            path=self.path.parent / snap_name,
        )
        self.next_seq = seq + 1
        self.compactions += 1
        self._base_refs = {snap_name}
        # Step 4 — GC, strictly after the successor is durable.  The
        # prior base chain stays: the fresh fallback copy's own record 0
        # still references it (single-failure tolerance); the NEXT
        # compaction retires whatever its replay no longer walks.
        keep = {snap_name, fallback_name} | base_refs
        removed: list[str] = []
        stale = sorted(
            self.path.parent.glob(f"{self.path.stem}.snapshot.*")
        ) + sorted(self.path.parent.glob(f"{self.path.name}.compacted.*"))
        for p in stale:
            if p.name in keep:
                continue
            try:
                self.store.unlink(p)
            except OSError:
                continue  # advisory — retried by the next compaction
            removed.append(p.name)
        return CompactionResult(
            seq=seq,
            snapshot_path=self.path.parent / snap_name,
            fallback_path=self.path.parent / fallback_name,
            folded_records=len(records),
            bytes_before=len(raw),
            bytes_after=len(anchor_line),
            removed=removed,
        )
