"""One host of the routed fleet: a daemon plus its capacity advertisement.

A :class:`ServiceMember` wraps one per-host
:class:`~evox_tpu.service.ServiceDaemon` (its own root, journal, and
executable cache) and gives the scheduling plane the two things a
:class:`~evox_tpu.service.TenantRouter` needs from a host:

* **Capacity advertisement over the heartbeat plane.**  The member's
  :meth:`capacity` snapshot — free lanes per compilation bucket, queue
  depth per admission class, the measured segment cadence, and
  exec-cache warmth — rides every
  :class:`~evox_tpu.parallel.HostHeartbeat` beat through the existing
  ``extra=`` payload hook, so the same ``host_<i>.json`` files that feed
  :class:`~evox_tpu.parallel.FleetHealth` liveness verdicts also carry
  the placement signal.  Nothing new on the wire: a fleet supervisor
  reading :func:`~evox_tpu.parallel.read_heartbeats` sees it for free.
* **A transport-shaped forward link.**  :meth:`request` speaks the exact
  ``(method, path, headers, body) -> (status, headers, body)`` interface
  :class:`~evox_tpu.resilience.FaultyTransport` wraps, so member-link
  chaos — dropped, torn, delayed, duplicated forwards — injects on the
  router→member seam with the same fixture the gateway's client seam
  uses.  The link carries only the mutating forwards (submit / steer /
  park); reads stay on the daemon's own read-only providers.

Replies are structured JSON and every refusal keeps the daemon's
machine-readable reason and retry hints, so the router can degrade a
failed forward to the gateway's 503 + ``Retry-After`` instead of
wedging.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Mapping, Union

from .daemon import ServiceDaemon, _bucket_label, _decode_spec
from .service import AdmissionError
from .tenant import TenantStatus

__all__ = ["ServiceMember", "MEMBER_API_PREFIX"]

#: Path prefix of the member forward link (the router-facing write API).
MEMBER_API_PREFIX = "/member/v1"

_JSON_HEADERS = {"Content-Type": "application/json"}

#: AdmissionError reason -> HTTP status on the member link.  Mirrors the
#: gateway's client-facing mapping so a refusal keeps its meaning across
#: the extra hop (429 = retryable overload, 503 = retryable fault,
#: 409 = non-retryable collision).
_REASON_STATUS = {
    "shed": 429,
    "queue-full": 503,
    "journal-failed": 503,
    "id-collision": 409,
    "uid-collision": 409,
    "uid-mismatch": 409,
}


class ServiceMember:
    """One fleet host: a :class:`~evox_tpu.service.ServiceDaemon` plus
    capacity advertisement and the router-facing forward link.

    :param index: this member's stable fleet index (its heartbeat
        ``process_index`` and the router's placement-record key).
    :param root: the member daemon's own root — per-host journal,
        tenant namespaces, and executable cache live under it.  Member
        roots must be distinct (the router enforces it).
    :param heartbeat_dir: the fleet's shared heartbeat directory
        (normally ``<router root>/heartbeats``).  ``None`` disables
        beats (the router then falls back to direct capacity reads and
        cannot render liveness verdicts for this member).
    :param heartbeat_interval: liveness-republish period of the beat
        thread (only relevant after :meth:`ServiceMember.heartbeat`'s
        ``start()``; the router beats synchronously each round).
    :param daemon: a pre-built daemon to wrap (tests / custom wiring);
        built from ``daemon_kwargs`` over ``root`` otherwise.
    :param daemon_kwargs: forwarded to :class:`ServiceDaemon` — the
        router requires ``seed`` / ``segment_steps`` to agree across
        members so a migrated tenant's trajectory stays bit-identical.
    """

    def __init__(
        self,
        index: int,
        root: Union[str, Path],
        *,
        heartbeat_dir: Union[str, Path, None] = None,
        heartbeat_interval: float = 0.5,
        daemon: ServiceDaemon | None = None,
        **daemon_kwargs: Any,
    ):
        if int(index) < 0:
            raise ValueError(f"member index must be >= 0, got {index}")
        self.index = int(index)
        self.root = Path(root)
        self.daemon = (
            daemon
            if daemon is not None
            else ServiceDaemon(self.root, **daemon_kwargs)
        )
        #: Router intent flags: a draining member takes no new
        #: placements (existing tenants run to completion); a retired
        #: one is read-only (results of completed tenants stay
        #: fetchable) and is never stepped or placed on again.
        self.draining = False
        self.retired = False
        self.heartbeat: Any | None = None
        if heartbeat_dir is not None:
            from ..parallel.multihost import HostHeartbeat

            self.heartbeat = HostHeartbeat(
                heartbeat_dir,
                process_index=self.index,
                interval=heartbeat_interval,
                extra=self.capacity,
                metrics=self.daemon._registry,
            )

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> int:
        """Start the wrapped daemon (journal replay); returns the number
        of tenants it restored.  Idempotent."""
        restored = self.daemon.start()
        self.beat()
        return restored

    def step(self) -> bool:
        """One scheduling round on this member's daemon, then a fresh
        progress beat (generation = segments run, so a frozen daemon
        with a live beat reads as *wedged*, not dead)."""
        busy = self.daemon.step()
        self.beat()
        return busy

    def beat(self, **fields: Any) -> None:
        """Publish one progress beat carrying the capacity payload
        (``extra=``).  No-op without a heartbeat directory."""
        if self.heartbeat is not None:
            self.heartbeat.beat(
                generation=self.daemon.service.stats.segments_run,
                segment_seconds=self.daemon._last_segment_seconds,
                **fields,
            )

    def close(self) -> None:
        if self.heartbeat is not None:
            self.heartbeat.stop()
        self.daemon.close()

    # -- capacity advertisement ----------------------------------------------
    def capacity(self) -> dict[str, Any]:
        """The placement signal, JSON-ready (it rides every heartbeat):
        tenant counts, free lanes per live compilation bucket, per-class
        queue depths, the measured segment cadence, and exec-cache
        warmth.  Read-only and snapshot-safe (endpoint/beat threads call
        it mid-boundary)."""
        svc = self.daemon.service
        running = queued = 0
        bucket_lanes: dict[str, int] = {}
        for rec in list(svc._tenants.values()):
            if rec.status is TenantStatus.RUNNING:
                running += 1
                if rec.bucket is not None:
                    label = _bucket_label(rec.bucket)
                    bucket_lanes[label] = bucket_lanes.get(label, 0) + 1
            elif rec.status is TenantStatus.QUEUED:
                queued += 1
        lanes = int(svc.lanes_per_pack)
        payload: dict[str, Any] = {
            "member": self.index,
            "draining": self.draining,
            "retired": self.retired,
            "tenants": len(svc._tenants),
            "running": running,
            "queued": queued,
            "lanes_per_pack": lanes,
            "bucket_lanes": bucket_lanes,
            "free_lanes": {
                label: max(0, lanes - used)
                for label, used in sorted(bucket_lanes.items())
            },
            "queue_depth": {
                name: self.daemon._class_depth(name)
                for name in sorted(self.daemon.classes)
            },
            "segment_seconds": self.daemon._last_segment_seconds,
        }
        cache = self.daemon.exec_cache
        if cache is not None:
            hits = int(getattr(cache.stats, "hits", 0))
            misses = int(getattr(cache.stats, "misses", 0))
            payload["exec_cache"] = {
                "hits": hits,
                "misses": misses,
                "hit_rate": hits / (hits + misses) if (hits + misses) else None,
            }
        if self.daemon.slo is not None:
            try:
                payload["slo"] = self.daemon.slo.describe()
            except Exception as e:  # noqa: BLE001 - advisory, never fatal
                payload["slo"] = {"error": f"{type(e).__name__}: {e}"}
        return payload

    def fault_events(self) -> list[tuple[int, str]]:
        """Injected disk-fault events observed by this member's store
        (``(save_index, kind)`` pairs when the store is a
        :class:`~evox_tpu.resilience.FaultyStore`; empty otherwise).
        The chaos conductor drains these into its canonical injected-
        event journal."""
        return list(getattr(self.daemon.store, "events", ()))

    def load(self) -> int:
        """Scalar placement load: live work on this member (running +
        queued).  The router breaks ties toward the lowest index."""
        svc = self.daemon.service
        return sum(
            1
            for rec in list(svc._tenants.values())
            if rec.status in (TenantStatus.RUNNING, TenantStatus.QUEUED)
        )

    # -- the forward link ----------------------------------------------------
    # The exact request() shape FaultyTransport wraps: the router holds a
    # transport per member (default: the member itself) and every
    # mutating forward crosses it, so link chaos composes with the same
    # fixture the gateway's client seam uses.
    def request(
        self,
        method: str,
        path: str,
        headers: Mapping[str, str] | None,
        body: bytes | None,
    ) -> tuple[int, dict[str, str], bytes]:
        """Serve one forwarded mutation.  Never raises: every failure is
        a structured JSON error reply (the transport layer above this —
        chaos injection — is what raises)."""
        try:
            status, payload = self._dispatch(method, path, body or b"")
        except AdmissionError as e:
            payload = {
                "error": e.reason,
                "detail": str(e),
                "retry_after_segments": e.retry_after_segments,
                "retry_after_seconds": e.retry_after_seconds,
            }
            status = _REASON_STATUS.get(e.reason, 400)
        except KeyError as e:
            status, payload = 404, {"error": "unknown-tenant", "detail": str(e)}
        except ValueError as e:
            status, payload = 400, {"error": "bad-request", "detail": str(e)}
        except RuntimeError as e:
            status, payload = 409, {"error": "conflict", "detail": str(e)}
        except Exception as e:  # noqa: BLE001 - a handler bug is a 500 reply
            status, payload = 500, {
                "error": type(e).__name__,
                "detail": str(e),
            }
        # Serving a forward IS a proof of life: refresh the beat (and the
        # capacity payload it carries) so a member busy compiling a burst
        # of submissions is not declared dead between scheduling rounds.
        try:
            self.beat()
        except Exception:  # noqa: BLE001 - liveness is advisory here
            pass
        return status, dict(_JSON_HEADERS), json.dumps(payload).encode("utf-8")

    def _dispatch(
        self, method: str, path: str, body: bytes
    ) -> tuple[int, dict[str, Any]]:
        if not path.startswith(MEMBER_API_PREFIX):
            return 404, {"error": "not-found", "detail": path}
        route = path[len(MEMBER_API_PREFIX):]
        if method == "GET" and route == "/capacity":
            return 200, self.capacity()
        if method != "POST":
            return 405, {"error": "method-not-allowed", "detail": method}
        try:
            payload = json.loads(body.decode("utf-8")) if body else {}
        except (ValueError, UnicodeDecodeError) as e:
            return 400, {"error": "bad-json", "detail": str(e)}
        if not isinstance(payload, dict):
            return 400, {"error": "bad-json", "detail": "body must be object"}
        if route == "/submit":
            return self._submit(payload)
        if route == "/steer":
            return self._steer(payload)
        if route == "/park":
            return self._park(payload)
        return 404, {"error": "not-found", "detail": path}

    def _submit(self, payload: dict[str, Any]) -> tuple[int, dict[str, Any]]:
        blob = payload.get("spec")
        if not isinstance(blob, str):
            return 400, {"error": "bad-spec", "detail": "spec blob required"}
        try:
            spec = _decode_spec(blob)
        except Exception as e:  # noqa: BLE001 - hostile blob = 400 reply
            return 400, {"error": "bad-spec", "detail": str(e)}
        record = self.daemon.submit(
            spec,
            tenant_class=str(payload.get("tenant_class", "standard")),
            journal_extra=payload.get("journal_extra") or None,
        )
        return 201, {
            "tenant_id": record.spec.tenant_id,
            "uid": int(record.uid),
            "status": record.status.value,
        }

    def _steer(self, payload: dict[str, Any]) -> tuple[int, dict[str, Any]]:
        tenant_id = str(payload.get("tenant_id", ""))
        knobs = self.daemon.steer(
            tenant_id,
            n_steps=payload.get("n_steps"),
            checkpoint_every=payload.get("checkpoint_every"),
            max_restarts=payload.get("max_restarts"),
            journal_extra=payload.get("journal_extra") or None,
        )
        return 200, {"tenant_id": tenant_id, "knobs": knobs}

    def _park(self, payload: dict[str, Any]) -> tuple[int, dict[str, Any]]:
        tenant_id = str(payload.get("tenant_id", ""))
        prior = self.daemon.park(tenant_id)
        record = self.daemon.tenant(tenant_id)
        return 200, {
            "tenant_id": tenant_id,
            "was": prior,
            "status": record.status.value,
        }
