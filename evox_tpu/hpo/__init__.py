"""Meta-optimization as a first-class workload (ROADMAP item 3).

The ``evox_tpu.hpo`` subsystem makes hyper-parameter optimization — an
entire inner workflow batch evaluated as the outer problem — a production
workload rather than a wrapper:

* :class:`NestedProblem` — the **fused nested runner**: one outer
  evaluate is ONE ``jax.vmap`` of the inner workflow's fused segment
  program, so ``outer_pop × inner_pop × inner_generations`` is a single
  XLA program, with identity-keyed (``fold_in(outer_key, candidate_uid)``)
  nested PRNG isolation and per-candidate inner telemetry batched out;
* :class:`HPORunner` — **resumable nested state**: outer + the full
  batch of inner states checkpoint through the existing resilient store,
  manifests record the inner algorithm/bucket metadata plus the
  per-candidate history ring, and a SIGTERM/SIGKILL mid-meta-run resumes
  bit-identically;
* :class:`GrowthLadder` / :class:`HPOGrowPolicy` — **elastic inner
  populations**: inner-run stagnation trends fire journaled
  ``Decision(kind="hpo-grow")`` records that regrow the ladder at
  segment boundaries, replayable bit-for-bit;
* the **service workload type** — ``TenantSpec(workload="hpo")`` packs
  meta-runs into :class:`~evox_tpu.service.OptimizationService` /
  :class:`~evox_tpu.service.ServiceDaemon` beside ordinary tenants with
  full bulkhead isolation, journal durability, exec-cache prewarm of the
  nested program, and per-tenant ``evox_hpo_*`` metrics.

:class:`HPOMonitor` / :class:`HPOFitnessMonitor` (the inner-run scoring
contract) live here too;
:mod:`evox_tpu.problems.hpo_wrapper` remains as a thin back-compat shim
over this subsystem.
"""

from .elastic import (
    GrowthLadder,
    HPOGrowPolicy,
    grow_evidence,
    validate_ladder_window,
)
from .monitor import HPO_REPEAT_AXIS, HPOFitnessMonitor, HPOMonitor
from .nested import NestedProblem, candidate_series, find_nested
from .runner import HPORunner

__all__ = [
    "HPO_REPEAT_AXIS",
    "HPOFitnessMonitor",
    "HPOMonitor",
    "NestedProblem",
    "HPORunner",
    "GrowthLadder",
    "HPOGrowPolicy",
    "candidate_series",
    "find_nested",
    "grow_evidence",
    "validate_ladder_window",
]
