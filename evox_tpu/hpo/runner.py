"""Resumable nested state: the meta-run supervisor.

:class:`HPORunner` is a :class:`~evox_tpu.resilience.ResilientRunner`
specialized for meta-optimization workflows (an outer
:class:`~evox_tpu.workflows.StdWorkflow` whose problem chain contains a
:class:`~evox_tpu.hpo.NestedProblem`):

* **checkpointing is whole-nest** — the outer state pytree already
  carries the full batch of inner instances plus the latest evaluation's
  telemetry, so the existing checkpoint store covers outer + inner state
  with no new format; every manifest additionally records the inner
  algorithm/bucket metadata (``manifest["hpo"]``) and the per-candidate
  inner-history ring, so a SIGTERM/SIGKILL mid-meta-run resumes
  bit-identically — outer state, inner instances, and the re-ingested
  per-candidate histories included (``tests/test_hpo_workload.py`` pins
  the matrix);
* **per-candidate inner telemetry** — at every segment boundary the
  nested telemetry (each candidate's per-generation inner best-fitness
  series) is ingested into host-side ``candidate_history`` (keyed by the
  stable candidate uid, deduplicated by outer generation so a resumed
  run's re-ingest never duplicates) and published as ``evox_hpo_*``
  metrics;
* **elastic growth** — with ``grow=GrowthLadder(...)`` and a
  :class:`~evox_tpu.control.Controller`, stagnation trends on the inner
  series fire journaled ``Decision(kind="hpo-grow")`` records through
  the runner's restart machinery (:class:`~evox_tpu.hpo.HPOGrowPolicy`):
  the inner population regrows at the boundary, the growth is restart
  lineage in every later manifest, and resume replays it bit-for-bit.
"""

from __future__ import annotations

from typing import Any, Union

import numpy as np

from ..core import State
from ..resilience.health import HealthProbe, HealthReport
from ..resilience.runner import ResilientRunner
from ..utils.checkpoint import read_manifest
from .elastic import (
    GrowthLadder,
    HPOGrowPolicy,
    grow_evidence,
    validate_ladder_window,
)
from .nested import NestedProblem, candidate_series, find_nested

__all__ = ["HPORunner"]


class HPORunner(ResilientRunner):
    """Checkpointed, trend-growing supervisor for one meta-optimization run.

    Usage::

        inner = StdWorkflow(OpenES(...), Sphere(), monitor=HPOFitnessMonitor())
        nested = NestedProblem(inner, iterations=32, num_candidates=64)
        outer = StdWorkflow(PSO(64, lb, ub), nested,
                            solution_transform=...)
        runner = HPORunner(outer, "ckpts/meta", checkpoint_every=4,
                           grow=GrowthLadder(inner_factory=make_inner,
                                             stagnation_window=8),
                           controller=Controller(journal=journal))
        runner.run(outer.init(key), n_steps=200)
        runner.candidate_history[uid]   # [(outer_gen, [inner best...]), ...]

    :param grow: optional :class:`~evox_tpu.hpo.GrowthLadder` — supplies
        the runner's restart policy (:class:`~evox_tpu.hpo.HPOGrowPolicy`),
        so ``restart=`` must not also be passed; growths share the
        ``max_restarts`` budget.  Trend-driven firing additionally needs
        ``controller=`` (decisions journal through it); without a
        controller the ladder only fires on threshold-probe unhealthy
        verdicts (IPOP's original trigger).
    :param history_limit: per-candidate inner-history entries persisted
        in each checkpoint manifest (the resume re-ingest ring; the
        in-memory history is unbounded).

    Every other parameter is
    :class:`~evox_tpu.resilience.ResilientRunner`'s.  ``health`` defaults
    to ``HealthProbe(nonfinite_skip=("instances",))`` — nested states
    legitimately carry ``inf`` placeholders in their *init* instances
    (monitor best-so-far, unevaluated fitness), which a default probe
    would misread as corruption.
    """

    def __init__(
        self,
        workflow: Any,
        checkpoint_dir: Union[str, "Any"],
        *,
        grow: GrowthLadder | None = None,
        health: HealthProbe | None = None,
        restart: Any | None = None,
        history_limit: int = 64,
        **kwargs: Any,
    ):
        nested = find_nested(getattr(workflow, "problem", None))
        if nested is None:
            raise ValueError(
                "HPORunner supervises meta-optimization workflows: the "
                "outer workflow's problem chain must contain a "
                "NestedProblem (evox_tpu.hpo)"
            )
        if grow is not None:
            if restart is not None:
                raise ValueError(
                    "grow= supplies the runner's restart policy "
                    "(HPOGrowPolicy); pass grow= or restart=, not both"
                )
            validate_ladder_window(grow, nested)
            restart = HPOGrowPolicy(grow)
        if health is None:
            health = HealthProbe(nonfinite_skip=("instances",))
        if history_limit < 1:
            raise ValueError(
                f"history_limit must be >= 1, got {history_limit}"
            )
        self.grow = grow
        self.history_limit = int(history_limit)
        #: Host-side inner histories: ``{candidate_uid: [(outer_generation,
        #: [per-inner-generation best fitness...]), ...]}`` — one entry per
        #: probed boundary, re-ingested from the manifest ring on resume.
        self.candidate_history: dict[int, list[tuple[int, list[float]]]] = {}
        self._last_series: dict[int, np.ndarray] = {}
        self._last_metric_gen = 0
        super().__init__(
            workflow,
            checkpoint_dir,
            health=health,
            restart=restart,
            **kwargs,
        )
        # Growth policies swap ``workflow.problem`` (the nested problem
        # regrows); remember the base configuration so every run() starts
        # from it and resume replays the recorded lineage on top — the
        # problem-side twin of the base class's ``_base_algorithm``.
        self._base_problem = getattr(workflow, "problem", None)

    # -- nested surface ------------------------------------------------------
    def _nested(self) -> NestedProblem:
        nested = find_nested(getattr(self.workflow, "problem", None))
        if nested is None:  # pragma: no cover - guarded at construction
            raise RuntimeError("workflow lost its NestedProblem")
        return nested

    def inner_history(self, uid: int) -> list[tuple[int, list[float]]]:
        """One candidate's ingested inner history (see
        :attr:`candidate_history`)."""
        return list(self.candidate_history.get(int(uid), []))

    def _reset_base_algorithm(self) -> None:
        super()._reset_base_algorithm()
        if (
            getattr(self, "_base_problem", None) is not None
            and self.workflow.problem is not self._base_problem
        ):
            self.workflow.problem = self._base_problem
            self._rebind_workflow()

    # -- manifests: inner metadata + the history ring ------------------------
    def _manifest_extras(self, probed, state=None) -> dict:
        extras = super()._manifest_extras(probed, state)
        nested = self._nested()
        from ..service.tenant import static_signature

        extras["hpo"] = {
            "inner_algorithm": type(nested.workflow.algorithm).__name__,
            "inner_pop": nested.inner_pop,
            "inner_dim": int(getattr(nested.workflow.algorithm, "dim", 0)),
            "iterations": nested.iterations,
            "num_candidates": nested.num_candidates,
            "num_repeats": nested.num_repeats,
            "bucket": static_signature(nested)[:16],
            "history": {
                str(uid): [
                    [int(g), [float(v) for v in series]]
                    for g, series in entries[-self.history_limit:]
                ]
                for uid, entries in self.candidate_history.items()
            },
        }
        return extras

    def resume(self, template: State) -> tuple[State, int] | None:
        result = super().resume(template)
        self.candidate_history = {}
        self._last_series = {}
        self._last_metric_gen = 0
        if result is None:
            return None
        _, gen = result
        self._last_metric_gen = int(gen)
        try:
            manifest = read_manifest(self._ckpt_path(gen)) or {}
        except Exception:  # noqa: BLE001 - history is best-effort metadata
            manifest = {}
        history = (manifest.get("hpo") or {}).get("history") or {}
        for uid, entries in history.items():
            restored = [
                (int(g), [float(v) for v in series]) for g, series in entries
            ]
            if restored:
                self.candidate_history[int(uid)] = restored
                self._last_series[int(uid)] = np.asarray(
                    restored[-1][1], dtype=float
                )
        if self.candidate_history:
            self._event(
                f"re-ingested inner histories for "
                f"{len(self.candidate_history)} candidate(s) from the "
                f"checkpoint manifest"
            )
        return result

    # -- boundary work: telemetry ingest + elastic growth --------------------
    def _hpo_boundary(self, state: State, done: int) -> None:
        """Ingest the boundary state's nested telemetry: per-candidate
        inner best-fitness series into :attr:`candidate_history` (dedup by
        outer generation — a resumed run re-probing its landing boundary
        appends exactly the entries the uninterrupted run did) plus the
        ``evox_hpo_*`` metrics."""
        nested = self._nested()
        if "problem" not in state:
            return
        prob = state["problem"]
        if self.obs is not None:
            outer_gens = max(int(done) - self._last_metric_gen, 0)
            if outer_gens:
                self.obs.counter(
                    "evox_hpo_inner_generations_total",
                    "Inner generations executed by the fused nested "
                    "evaluate (candidates x repeats x iterations).",
                ).inc(outer_gens * nested.inner_generations_per_eval())
            self.obs.gauge(
                "evox_hpo_inner_pop",
                "Inner population size of the nested problem (grows "
                "under the elastic ladder).",
            ).set(float(nested.inner_pop))
            self.obs.gauge(
                "evox_hpo_candidates",
                "Outer candidates per nested evaluation.",
            ).set(float(nested.num_candidates))
        self._last_metric_gen = int(done)
        for uid, series in candidate_series(prob).items():
            entries = self.candidate_history.setdefault(uid, [])
            if entries and entries[-1][0] >= int(done):
                continue  # already ingested (resume re-probe)
            entries.append((int(done), [float(v) for v in series]))
            self._last_series[uid] = series

    def _consult_grow(self, done: int):
        """Consult the controller's ``hpo-grow`` plane with the newest
        per-candidate inner series; returns the fired
        :class:`~evox_tpu.control.Decision` or ``None``.  Never raises —
        the controller guards itself, and this wrapper is the
        belt-and-braces outer guard (the same contract as the base
        trend consult)."""
        try:
            evidence = grow_evidence(
                self.grow, self._last_series, self._nested().inner_pop
            )
            if evidence is None:
                return None
            return self.controller.hpo_grow(
                evidence=evidence, generation=done
            )
        except Exception as e:  # noqa: BLE001 - advisory plane only
            self._event(
                f"hpo-grow consult failed ({type(e).__name__}: {e}); "
                f"continuing without growth",
                warn=True,
                category="control",
            )
            return None

    def _health_boundary(
        self, state: State, done: int, n_steps: int
    ) -> tuple[State, int]:
        self._hpo_boundary(state, done)
        if (
            self.grow is not None
            and self.controller is not None
            and done < n_steps
            and len(self.stats.restarts) < self.max_restarts
        ):
            decision = self._consult_grow(done)
            if decision is not None and decision.action not in ("", "hold"):
                report = HealthReport(
                    generation=done, healthy=True
                ).with_trend(
                    [
                        f"hpo-grow: inner-run stagnation (candidate uid "
                        f"{decision.evidence.get('candidate_uid')}, "
                        f"inner pop {decision.evidence.get('inner_pop')} "
                        f"-> {decision.action})"
                    ]
                )
                # Growth rides the restart machinery: lineage event,
                # post-growth checkpoint, stale-future invalidation —
                # needs_init=False, so the outer search continues
                # untouched at the grown inner shape.
                return self._fire_restart(
                    state, done, n_steps, report, decision
                )
        return super()._health_boundary(state, done, n_steps)
