"""HPO inner-run monitors: how an inner workflow reports its score.

The meta-optimization contract: the inner workflow's monitor must expose
the run's final score via ``tell_fitness(state)`` — that scalar (or
per-objective vector) is the outer problem's fitness for the
hyper-parameter set the run evaluated (reference
``hpo_wrapper.py:41-58``).

``num_repeats`` semantics match the reference exactly: with repeats, the
*algorithm* in each repeat lane adapts on its own raw fitness, while the
*monitor* aggregates fitness across repeats **inside every generation**
(mean by default) before updating its best — "best of per-generation
mean" (reference ``hpo_wrapper.py:19-38`` custom-op aggregation +
``:83-96``).  The reference needs a vmap-aware ``torch.library`` custom
op for that cross-lane mean; in JAX it is a named-axis collective: the
repeat vmap carries ``axis_name=HPO_REPEAT_AXIS`` and the monitor
reduces over it with ``lax.all_gather``.  The simpler end-of-run
estimator (aggregate each lane's final best) remains available as
``aggregation="final"`` on the wrapping problem.
"""

from __future__ import annotations

import contextvars
from typing import Callable

import jax
import jax.numpy as jnp

from ..core import Monitor, State

__all__ = ["HPOMonitor", "HPOFitnessMonitor", "HPO_REPEAT_AXIS"]

#: vmap axis name carried by the repeats axis inside
#: :meth:`NestedProblem.evaluate <evox_tpu.hpo.NestedProblem.evaluate>`;
#: HPO monitors reduce over it.
HPO_REPEAT_AXIS = "hpo_repeat"

#: Trace-scoped repeat wiring ``(num_repeats, fit_aggregation)`` installed by
#: :meth:`NestedProblem.evaluate` for the duration of its trace.  A
#: ``ContextVar`` (not attribute mutation on the shared monitor object) so
#: that (a) concurrent traces in different threads/contexts cannot observe
#: each other's wiring, and (b) nested wrappers (HPO-of-HPO) save/restore
#: correctly via token reset.
_REPEAT_WIRING: contextvars.ContextVar[tuple[int, Callable] | None] = (
    contextvars.ContextVar("hpo_repeat_wiring", default=None)
)


def _reduce_axis(fn: Callable, arr: jax.Array, axis: int) -> jax.Array:
    """Apply a repeats reduction.  Preferred contract is ``fn(arr, axis=...)``
    (like ``jnp.mean``); 1-D reducers ``fn(vec) -> scalar`` are accepted for
    back-compat and applied along ``axis``."""
    try:
        return fn(arr, axis=axis)
    except TypeError:
        return jnp.apply_along_axis(fn, axis, arr)


class HPOMonitor(Monitor):
    """Base monitor for HPO inner workflows: must expose the inner run's
    final score via ``tell_fitness`` (reference ``hpo_wrapper.py:41-58``).

    Subclasses aggregate each generation's fitness across repeats by
    calling :meth:`aggregate_repeats` in ``pre_tell`` — never by reading
    ``self.num_repeats`` directly: when the monitor runs inside a
    :class:`~evox_tpu.hpo.NestedProblem` evaluation, the wrapper's
    trace-scoped wiring (repeat count + reduction) takes precedence over
    the constructor values, and only ``aggregate_repeats`` sees it.

    :param num_repeats: repeat count used when the monitor runs standalone
        (outside a wrapper's trace).
    :param fit_aggregation: reduction over the repeats axis, called as
        ``fit_aggregation(stacked, axis=0)`` (default ``jnp.mean`` — the
        reference's mean-of-repeats, ``hpo_wrapper.py:19-38``).
    """

    def __init__(
        self,
        num_repeats: int = 1,
        fit_aggregation: Callable = jnp.mean,
    ):
        self.num_repeats = num_repeats
        self.fit_aggregation = fit_aggregation

    def aggregate_repeats(self, fitness: jax.Array) -> jax.Array:
        """Cross-repeat aggregation of this generation's fitness.  Inside the
        wrapper's repeat vmap this is a collective over the named axis: every
        lane receives the same aggregated tensor (the JAX-native equivalent
        of the reference's vmap-registered mean custom op).

        Repeat wiring installed by a surrounding
        :meth:`NestedProblem.evaluate` trace (via the context-local
        ``_REPEAT_WIRING``) takes precedence over the constructor
        attributes, so one monitor instance can serve several wrappers."""
        wiring = _REPEAT_WIRING.get()
        num_repeats, fit_aggregation = (
            wiring if wiring is not None
            else (self.num_repeats, self.fit_aggregation)
        )
        if num_repeats <= 1:
            return fitness
        try:
            stacked = jax.lax.all_gather(fitness, HPO_REPEAT_AXIS, axis=0)
        except NameError:
            # The repeat axis is only bound inside NestedProblem's
            # per-generation vmap; running the same (already-wired) monitor
            # standalone or under "final" aggregation traces with no such
            # axis — degrade to the raw per-lane fitness.
            return fitness
        return _reduce_axis(fit_aggregation, stacked, 0)

    def tell_fitness(self, state: State) -> jax.Array:
        """The scalar (or per-objective) fitness this inner run reports to
        the outer algorithm.  Abstract: subclasses define what "fitness of
        a run" means (e.g. best-so-far)."""
        raise NotImplementedError(
            "`tell_fitness` function is not implemented. It must be overwritten."
        )


class HPOFitnessMonitor(HPOMonitor):
    """Tracks the best fitness value seen by the inner workflow
    (reference ``hpo_wrapper.py:61-103``)."""

    def __init__(
        self,
        multi_obj_metric: Callable | None = None,
        num_repeats: int = 1,
        fit_aggregation: Callable = jnp.mean,
    ):
        """
        :param multi_obj_metric: scalarizing metric for multi-objective inner
            problems, e.g. ``lambda f: igd(f, problem.pf())``; unused for
            single-objective.
        """
        if multi_obj_metric is not None and not callable(multi_obj_metric):
            raise ValueError(
                f"Expect `multi_obj_metric` to be `None` or callable, got "
                f"{multi_obj_metric}"
            )
        super().__init__(num_repeats, fit_aggregation)
        self.multi_obj_metric = multi_obj_metric

    def setup(self, key: jax.Array) -> State:
        del key
        return State(best_fitness=jnp.asarray(jnp.inf))

    def pre_tell(self, state: State, fitness: jax.Array) -> State:
        fitness = self.aggregate_repeats(fitness)
        if fitness.ndim == 1:
            value = jnp.min(fitness)
        else:
            value = self.multi_obj_metric(fitness)
        return state.replace(
            best_fitness=jnp.minimum(value, state.best_fitness)
        )

    def tell_fitness(self, state: State) -> jax.Array:
        """Best fitness seen over the inner run (the wrapped workflow's
        objective value for these hyper-parameters)."""
        return state.best_fitness
