"""The fused nested runner: an entire inner workflow batch as ONE program.

:class:`NestedProblem` is the meta-optimization core (ROADMAP item 3,
EvoX's ``HPOProblemWrapper`` capability): the outer population is a batch
of hyper-parameter sets, and evaluating it runs ``num_candidates``
independent copies of an inner :class:`~evox_tpu.workflows.StdWorkflow`
for ``iterations`` generations — as **one** XLA program.  Where the seed
prototype (``problems/hpo_wrapper.py``) looped a plain ``fori_loop`` of
``step``, the evaluate here is one ``jax.vmap`` of the inner workflow's
fused segment program (:meth:`StdWorkflow._segment_program
<evox_tpu.workflows.StdWorkflow._segment_program>` — the PR-6 ``lax.scan``
with quarantine and monitor counters inside the compiled body), so
``outer_pop × inner_pop × segment_generations`` compiles and dispatches as
a single program **and** every inner run's per-generation best-fitness
series rides out as telemetry the meta-layers consume:

* :class:`~evox_tpu.hpo.HPORunner` re-ingests it per candidate at every
  checkpoint boundary (host-side inner histories, persisted in manifests);
* the elastic-growth ladder (:mod:`evox_tpu.hpo.elastic`) reads it for
  per-candidate stagnation trends behind journaled ``hpo-grow`` decisions;
* the service layer publishes it as per-tenant ``evox_hpo_*`` metrics.

**Nested PRNG contract** (``prng="uid"``, the default): each candidate's
inner instance keys derive by ``fold_in(outer_key, candidate_uid)`` — the
GL006/identity-keyed discipline the service applies to tenants.  The uid
is a *stable identity* carried in the problem state (``state.uids``),
never a lane/batch position, so a candidate's inner randomness is
invariant under re-packing, eviction/readmission, and population regrowth
of its neighbors.  Repeat lanes fold the repeat index into the candidate
key (a stable identity of the repeat lane) and compose with the
:data:`~evox_tpu.hpo.HPO_REPEAT_AXIS` per-generation aggregation exactly
like the seed wrapper.  ``prng="split"`` keeps the seed wrapper's
``jax.random.split`` schedule for back-compat
(:class:`~evox_tpu.problems.hpo_wrapper.HPOProblemWrapper` uses it, so
its published semantics — and ``tests/test_hpo_wrapper.py`` — are
unchanged).

Inner states are consumed per evaluation: every evaluate starts from the
identical init instances (the reference's ``copy_init_state`` behavior),
so the problem state the outer workflow threads is static search
infrastructure plus the latest evaluation's telemetry.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Literal, Mapping

import jax
import jax.numpy as jnp

from ..core import Problem, State, Workflow, get_params, set_params
from .monitor import HPO_REPEAT_AXIS, HPOMonitor, _REPEAT_WIRING, _reduce_axis

__all__ = ["NestedProblem", "candidate_series", "find_nested"]


def candidate_series(problem_state: Any) -> dict[int, Any]:
    """Per-candidate inner best-fitness series from a nested problem
    sub-state's telemetry (repeat lanes averaged), keyed by the stable
    candidate uid — the host-side evidence feed for histories and the
    growth ladder.  ONE definition shared by
    :meth:`HPORunner._hpo_boundary <evox_tpu.hpo.HPORunner>` and the
    service's per-tenant grow consult, so both compute identical
    evidence.  Empty dict when the state carries no usable telemetry."""
    import numpy as np

    if (
        problem_state is None
        or "telemetry" not in problem_state
        or "uids" not in problem_state
    ):
        return {}
    tel = jax.device_get(problem_state["telemetry"])
    if "best_fitness" not in tel:
        return {}
    series = np.asarray(tel["best_fitness"])
    if series.ndim == 3:  # (candidates, repeats, inner generations)
        series = series.mean(axis=1)
    uids = np.asarray(jax.device_get(problem_state["uids"]))
    return {int(u): series[i] for i, u in enumerate(uids)}


def find_nested(problem: Any) -> "NestedProblem | None":
    """The :class:`NestedProblem` inside a problem wrapper chain (fault
    injection, transforms), or ``None``.  Mirrors
    ``parallel.find_sharded`` so the meta-layers detect the HPO surface
    through any composition."""
    from ..parallel import iter_problem_chain

    for p in iter_problem_chain(problem):
        if getattr(p, "hpo_nested", False):
            return p
    return None


class NestedProblem(Problem):
    """An inner workflow batch as an outer ``Problem`` — the fused nested
    evaluate (see the module docstring for the program shape and PRNG
    contract).

    Usage::

        inner = StdWorkflow(PSO(64, lb, ub), Sphere(),
                            monitor=HPOFitnessMonitor())
        nested = NestedProblem(inner, iterations=32, num_candidates=16)
        outer = StdWorkflow(OpenES(...), nested,
                            solution_transform=lambda x: {"algorithm.w": x[:, 0]})

    :param workflow: the inner workflow; its monitor must be an
        :class:`~evox_tpu.hpo.HPOMonitor` (``tell_fitness`` defines the
        score of a run).
    :param iterations: total inner generations per evaluation, including
        the init and final steps (reference semantics; >= 2).  The middle
        ``iterations - 2`` generations are the fused ``lax.scan``.
    :param num_candidates: parallel inner-workflow instances = outer
        population size.
    :param num_repeats: independent repeats per candidate (distinct PRNG
        streams); hyper-parameters are shared across repeats.
    :param fit_aggregation: reduction over the repeats axis, called as
        ``fit_aggregation(stacked, axis=0)``; default ``jnp.mean``.
    :param aggregation: ``"per_generation"`` (reference-faithful: the
        monitor sees repeat-aggregated fitness every generation and
        tracks best-of-mean) or ``"final"`` (each repeat lane tracks its
        own best; the lanes' final scores are aggregated once).
    :param prng: ``"uid"`` (default — identity-keyed
        ``fold_in(outer_key, candidate_uid)`` instance streams, the
        GL006 discipline) or ``"split"`` (the seed wrapper's
        ``jax.random.split`` schedule, kept for back-compat).
    :param telemetry: carry each evaluation's inner telemetry
        (per-generation best-fitness series, executed counts) in the
        problem state (``state.telemetry``) for the meta-layers to read
        at boundaries.  Costs ``num_candidates × num_repeats ×
        (iterations - 2)`` scalars of state; ``False`` drops it (the
        back-compat shim's default).
    :param base_uid: first candidate uid (uids are
        ``base_uid .. base_uid + num_candidates - 1``); offset it when
        several nested problems share one outer key space.
    """

    #: Marker the service layer's ``workload="hpo"`` validation and the
    #: meta-layers' wrapper-chain walk (:func:`find_nested`) key on.
    hpo_nested = True

    def __init__(
        self,
        workflow: Workflow,
        iterations: int,
        num_candidates: int,
        *,
        num_repeats: int = 1,
        fit_aggregation: Callable = jnp.mean,
        aggregation: Literal["per_generation", "final"] = "per_generation",
        prng: Literal["uid", "split"] = "uid",
        telemetry: bool = True,
        base_uid: int = 0,
    ):
        if iterations < 2:
            raise ValueError(
                f"iterations must be at least 2 (init + final), got "
                f"{iterations}"
            )
        if num_candidates < 1:
            raise ValueError(
                f"num_candidates must be >= 1, got {num_candidates}"
            )
        if num_repeats < 1:
            raise ValueError(f"num_repeats must be >= 1, got {num_repeats}")
        if aggregation not in ("per_generation", "final"):
            raise ValueError(
                f"aggregation must be 'per_generation' or 'final', got "
                f"{aggregation!r}"
            )
        if prng not in ("uid", "split"):
            raise ValueError(f"prng must be 'uid' or 'split', got {prng!r}")
        if base_uid < 0:
            raise ValueError(f"base_uid must be >= 0, got {base_uid}")
        monitor = getattr(workflow, "monitor", None)
        if not isinstance(monitor, HPOMonitor):
            raise ValueError(
                f"Expect workflow monitor to be `HPOMonitor`, got "
                f"{type(monitor)}"
            )
        if not hasattr(workflow, "_segment_program"):
            raise ValueError(
                f"NestedProblem needs an inner workflow exposing the fused "
                f"segment builder (_segment_program); got "
                f"{type(workflow).__name__}"
            )
        self.workflow = workflow
        self.iterations = int(iterations)
        self.num_candidates = int(num_candidates)
        self.num_repeats = int(num_repeats)
        self.fit_aggregation = fit_aggregation
        self.aggregation = aggregation
        self.prng = prng
        self.telemetry = bool(telemetry)
        self.base_uid = int(base_uid)
        self._seg_cfg = None

    # -- pickling (the serving daemon journals specs) -----------------------
    def __getstate__(self) -> dict:
        d = dict(self.__dict__)
        d["_seg_cfg"] = None  # NamedTuple, but rebuilt cheaply anyway
        wf = copy.copy(d["workflow"])
        # The inner workflow's cached jit wrapper holds compiled-program
        # handles that cannot (and must not) cross a process boundary.
        if hasattr(wf, "_segment_jit"):
            wf._segment_jit = None
        d["workflow"] = wf
        return d

    # -- derived configuration ----------------------------------------------
    @property
    def inner_pop(self) -> int:
        """The inner algorithm's population size (the elastic-growth
        ladder's regrow axis)."""
        return int(getattr(self.workflow.algorithm, "pop_size", 0))

    def inner_generations_per_eval(self) -> int:
        """Inner generations one outer evaluation executes across all
        candidates and repeats (``evox_hpo_inner_generations_total``'s
        increment per outer generation)."""
        return self.num_candidates * self.num_repeats * self.iterations

    # Trace-time memoization of a STATIC config (hashable NamedTuple, the
    # same value every trace) — the segment-jit-cache idiom, not state.
    def _cfg(self):  # graftlint: disable=GL005
        if self._seg_cfg is None:
            # One shape for every nesting level: capture off (sink history
            # belongs to the inner monitor's in-state score, not host
            # callbacks — an io_callback under the candidate vmap could not
            # be ordered anyway), metrics off (the per-generation
            # best_fitness channel IS the meta-telemetry), barrier-free
            # (the shape that vmaps; no early stop, so it changes nothing).
            self._seg_cfg = self.workflow.segment_config(
                capture_history=False,
                metrics=False,
                stop_on_unhealthy=False,
                barrier=False,
            )
        return self._seg_cfg

    # -- state construction ---------------------------------------------------
    def _candidate_uids(self) -> jax.Array:
        return jnp.arange(self.num_candidates, dtype=jnp.uint32) + jnp.uint32(
            self.base_uid
        )

    def setup(self, key: jax.Array) -> State:
        n, r = self.num_candidates, self.num_repeats
        uids = self._candidate_uids()
        if self.prng == "uid":
            # Identity-keyed instance streams (the GL006 discipline): the
            # candidate uid — a stable identity, never a lane position —
            # keys the candidate; the repeat index (a stable identity of
            # the repeat lane) keys the repeat.
            cand_keys = jax.vmap(
                lambda uid: jax.random.fold_in(key, uid)
            )(uids)
            if r > 1:
                reps = jnp.arange(r, dtype=jnp.uint32)
                keys = jax.vmap(
                    lambda ck: jax.vmap(
                        lambda rep: jax.random.fold_in(ck, rep)
                    )(reps)
                )(cand_keys)
                stacked = jax.vmap(jax.vmap(self.workflow.setup))(keys)
            else:
                stacked = jax.vmap(self.workflow.setup)(cand_keys)
        else:
            # Back-compat: the seed wrapper's split schedule, bit-for-bit.
            flat_keys = jax.random.split(key, n * r)
            stacked = jax.vmap(self.workflow.setup)(flat_keys)
            if r > 1:
                stacked = jax.tree.map(
                    lambda x: x.reshape((n, r) + x.shape[1:]), stacked
                )
        state = State(instances=stacked, uids=uids)
        if self.telemetry:
            state = state.replace(telemetry=self._zero_telemetry(stacked))
        return state

    def get_init_params(self, state: State) -> dict[str, jax.Array]:
        """The stacked hyper-parameter dict of the inner workflow: every
        ``Parameter``-labeled leaf, keyed by dotted path, with leading
        ``(num_candidates,)`` axis (repeats share hyper-parameters)."""
        params = get_params(state.instances)
        if self.num_repeats > 1:
            params = {k: v[:, 0] for k, v in params.items()}
        return params

    def get_params_keys(self, state: State) -> list[str]:
        """Dotted paths of every tunable (``Parameter``-labeled) leaf."""
        return list(self.get_init_params(state).keys())

    # -- the fused nested evaluate --------------------------------------------
    def _run_one(self, ws: State, hp: Mapping[str, Any]):
        """One inner run: init, the fused multi-generation segment, final —
        returns ``(tell_fitness, telemetry State)``."""
        wf = self.workflow
        ws = set_params(ws, hp)
        ws = wf.init_step(ws)
        inner = self.iterations - 2
        if inner > 0:
            ws, raw = wf._segment_program(ws, inner, self._cfg())
        else:
            raw = None
        ws = wf.final_step(ws)
        return wf.monitor.tell_fitness(ws.monitor), self._pack_telemetry(raw)

    @staticmethod
    def _pack_telemetry(raw: Any) -> State:
        if raw is None:  # iterations == 2: no fused middle segment
            return State(executed=jnp.int32(0))
        out: dict[str, Any] = {
            "executed": raw["executed"],
            "stopped": raw["stopped"],
        }
        if "best_fitness" in raw:
            out["best_fitness"] = raw["best_fitness"]
        return State(**out)

    def _run_batch(self, instances: State, hp: Mapping[str, Any]):
        """The whole outer evaluation: ONE ``jax.vmap`` (two, with
        repeats) of the fused inner run over candidates.  Returns
        ``(fitness (num_candidates,), telemetry)``."""
        hp = dict(hp)
        if self.num_repeats == 1:
            return jax.vmap(self._run_one)(instances, hp)
        if self.aggregation == "per_generation":
            # Repeat lanes run under a *named* vmap axis; the monitor's
            # ``aggregate_repeats`` all-gathers over it each generation,
            # so every lane's best tracks the aggregated (mean) fitness
            # and the lanes' final tells are identical — read lane 0.
            fit, tel = jax.vmap(
                lambda ws, h: jax.vmap(
                    lambda w: self._run_one(w, h),
                    axis_name=HPO_REPEAT_AXIS,
                )(ws)
            )(instances, hp)
            return fit[:, 0], tel
        # "final": aggregate each lane's independent end-of-run best.
        fit, tel = jax.vmap(
            lambda ws, h: jax.vmap(lambda w: self._run_one(w, h))(ws)
        )(instances, hp)
        return _reduce_axis(self.fit_aggregation, fit, 1), tel

    def _wiring(self) -> tuple[int, Callable]:
        per_gen = self.aggregation == "per_generation" and self.num_repeats > 1
        return (
            (self.num_repeats, self.fit_aggregation)
            if per_gen
            else (1, jnp.mean)
        )

    def _zero_telemetry(self, instances: State):
        """Zeros shaped like one evaluation's telemetry — the problem
        state carries the telemetry from construction so its pytree
        structure never changes across steps (a checkpoint/template
        invariant).  Abstract (``jax.eval_shape``): no device code runs."""
        params = get_params(instances)
        if self.num_repeats > 1:
            params = {k: v[:, 0] for k, v in params.items()}
        token = _REPEAT_WIRING.set(self._wiring())
        try:
            struct = jax.eval_shape(
                lambda inst, hp: self._run_batch(inst, hp)[1],
                instances,
                params,
            )
        finally:
            _REPEAT_WIRING.reset(token)
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), struct
        )

    def evaluate(
        self, state: State, hyper_parameters: Mapping[str, Any]
    ) -> tuple[jax.Array, State]:
        # Wire the monitor's repeat aggregation for the duration of this
        # trace only, via the context-local ``_REPEAT_WIRING`` (several
        # wrappers may share one workflow object, and concurrent traces
        # must not observe each other's config, so nothing is mutated on
        # the shared monitor).
        token = _REPEAT_WIRING.set(self._wiring())
        try:
            fit, tel = self._run_batch(state.instances, hyper_parameters)
        finally:
            _REPEAT_WIRING.reset(token)
        # The inner states are consumed per evaluation (fresh instances
        # each call evaluate identical init states, matching the
        # reference's copy_init_state behavior); only the telemetry of
        # the latest evaluation threads forward.
        if self.telemetry and "telemetry" in state:
            state = state.replace(telemetry=tel)
        return fit, state

    # -- elastic growth surface ----------------------------------------------
    def with_inner_workflow(self, workflow: Workflow) -> "NestedProblem":
        """A copy of this configuration over a different inner workflow
        (the elastic-growth re-key: a changed inner population changes
        the compiled program, the bucket key, and every state shape)."""
        return type(self)(
            workflow,
            self.iterations,
            self.num_candidates,
            num_repeats=self.num_repeats,
            fit_aggregation=self.fit_aggregation,
            aggregation=self.aggregation,
            prng=self.prng,
            telemetry=self.telemetry,
            base_uid=self.base_uid,
        )

    def with_inner_pop(
        self, pop_size: int, inner_factory: Callable[[int], Any]
    ) -> "NestedProblem":
        """A copy with the inner algorithm regrown to ``pop_size`` via
        ``inner_factory`` — same inner problem/monitor/transforms, larger
        population (the IPOP regrow axis)."""
        from ..workflows import StdWorkflow

        wf = self.workflow
        new_wf = StdWorkflow(
            inner_factory(int(pop_size)),
            wf.problem,
            monitor=wf.monitor,
            opt_direction="min" if wf.opt_direction == 1 else "max",
            solution_transform=wf.solution_transform,
            fitness_transform=wf.fitness_transform,
            quarantine_nonfinite=wf.quarantine_nonfinite,
            nonfinite_penalty=wf.nonfinite_penalty,
            # Numerics identity survives elastic regrowth: dropping the
            # policy/impl here would silently widen a bf16 inner run (or
            # fork its streams) at the first hpo-grow boundary.
            precision=getattr(wf, "precision", None),
            key_impl=getattr(wf, "key_impl", None),
        )
        return self.with_inner_workflow(new_wf)

    def regrow_state(self, old_state: State, salt: int) -> State:
        """A fresh problem sub-state for THIS (regrown) configuration,
        derived deterministically from the old state's PRNG identity plus
        ``salt`` — a pure function of ``(old state, salt)``, so a resumed
        run replaying a journaled growth lineage rebuilds bit-identical
        instances.  Candidate uids (and with them the identity-keyed
        stream discipline) are preserved by construction."""
        base = None
        for leaf in jax.tree_util.tree_leaves(old_state):
            if isinstance(leaf, jax.Array) and jax.dtypes.issubdtype(
                leaf.dtype, jax.dtypes.prng_key
            ):
                base = leaf.reshape(-1)[0]
                break
        if base is None:
            base = jax.random.key(0)
        return self.setup(jax.random.fold_in(base, jnp.uint32(salt)))
