"""Elastic inner populations: the controller-driven IPOP growth ladder.

"Massively parallel CMA-ES with increasing population" (PAPERS.md) as a
*meta*-behavior: when a candidate's **inner** run stagnates — detected
from the per-candidate best-fitness series the fused nested evaluate
batches out as telemetry — the control plane fires a journaled
``Decision(kind="hpo-grow")`` and the next segment boundary regrows the
nested problem's inner population (``pop * growth_factor``, capped),
rebuilding every candidate's instances at the larger size from the
identity-keyed streams.  Growth is deliberately **whole-ladder**: all
candidates share one compiled program (one vmap batch), so the regrow
axis is the nested problem's inner population — the stagnating candidate
that *triggered* it is recorded in the decision's evidence
(``candidate_uid``), and every candidate keeps its uid-keyed PRNG
identity through the regrow (the IPOP semantics: restart bigger, keep
searching; the hyper-parameters under optimization live in the OUTER
state, which a growth never touches).

Two consumers:

* :class:`~evox_tpu.hpo.HPORunner` — :class:`HPOGrowPolicy` rides the
  runner's restart machinery: fired growths are
  :class:`~evox_tpu.resilience.RestartEvent` lineage (policy
  ``"hpo-grow"``), persisted in every checkpoint manifest, and replayed
  by resume via :meth:`HPOGrowPolicy.rebuild_template` — a run killed
  after a growth resumes bit-identically at the grown shape.
* :class:`~evox_tpu.service.OptimizationService` — an HPO tenant whose
  spec carries a ladder is regrown by **bucket re-key + lane surgery**:
  the grown nested problem keys a different compilation bucket, the
  tenant's lane is released from the old pack and its (outer-preserved,
  inner-regrown) state admitted into the new bucket's pack.

Decisions are replayable bit-for-bit: the action is the pure
:func:`~evox_tpu.control.controller.decide_hpo_grow` over the journaled
evidence (``Controller.replay_decisions`` covers ``hpo-grow`` records
like every other kind).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

from ..resilience.restart import RestartContext, RestartPolicy, perturb_prng_keys
from .nested import NestedProblem, find_nested

__all__ = [
    "GrowthLadder",
    "HPOGrowPolicy",
    "grow_evidence",
    "validate_ladder_window",
]


def validate_ladder_window(ladder: "GrowthLadder", nested: Any) -> None:
    """A ladder whose stagnation window exceeds what one evaluation's
    telemetry can ever span would silently never fire — fail loudly at
    construction instead (one definition for the solo runner and the
    service spec).  The series holds ``iterations - 2`` points and the
    windowed slope needs ``span >= window``, so firing requires
    ``iterations >= stagnation_window + 3``."""
    window = int(getattr(ladder, "stagnation_window", 0))
    iterations = int(getattr(nested, "iterations", 0))
    if iterations < window + 3:
        raise ValueError(
            f"GrowthLadder(stagnation_window={window}) can never fire "
            f"against NestedProblem(iterations={iterations}): one "
            f"evaluation's telemetry series holds iterations-2 = "
            f"{iterations - 2} points and the windowed slope needs "
            f"span >= window (iterations >= stagnation_window + 3); "
            f"shrink the window or raise iterations"
        )


@dataclass
class GrowthLadder:
    """Configuration of the elastic inner-population ladder.

    :param inner_factory: ``pop_size -> Algorithm`` builder for the
        regrown inner algorithm (same hyperparameters, new population
        size) — the :class:`~evox_tpu.resilience.ReinitLargerPopulation`
        contract, applied to the *inner* side of the nesting.  Resume and
        journal replay need the same factory configured.
    :param growth_factor: multiplicative population growth per firing
        (IPOP default 2.0; must be > 1).
    :param max_inner_pop: hard cap on the regrown inner population
        (``None`` = uncapped).
    :param stagnation_window: inner generations of best-fitness span a
        candidate's series must cover before the stagnation detector may
        fire (must be >= 1; series shorter than the window never fire).
    :param stagnation_tol: minimum projected best-fitness improvement
        (minimizing frame) across the window that counts as progress.
    :param salt: PRNG fold salt for the deterministic instance rebuild
        (offset by the growth index).
    """

    inner_factory: Callable[[int], Any]
    growth_factor: float = 2.0
    max_inner_pop: int | None = None
    stagnation_window: int = 8
    stagnation_tol: float = 0.0
    salt: int = 0x6B0B

    def __post_init__(self) -> None:
        if self.growth_factor <= 1.0:
            raise ValueError(
                f"growth_factor must be > 1.0 (the population must grow), "
                f"got {self.growth_factor}"
            )
        if self.max_inner_pop is not None and self.max_inner_pop < 1:
            raise ValueError(
                f"max_inner_pop must be >= 1, got {self.max_inner_pop}"
            )
        if self.stagnation_window < 1:
            raise ValueError(
                f"stagnation_window must be >= 1, got "
                f"{self.stagnation_window}"
            )

    def next_pop(self, current: int) -> int:
        """The pop a firing grows ``current`` to (>= current + 1 unless
        capped; a capped ladder returns ``current`` — nothing to grow)."""
        new_pop = max(int(round(current * self.growth_factor)), current + 1)
        if self.max_inner_pop is not None:
            new_pop = min(new_pop, self.max_inner_pop)
        return max(new_pop, current)

    def evidence(
        self,
        *,
        candidate_uid: int,
        best_slope: float | None,
        span: float,
        inner_pop: int,
    ) -> dict[str, Any]:
        """The journaled evidence dict behind one grow consult — measured
        signals plus the thresholds in force, so
        :func:`~evox_tpu.control.controller.decide_hpo_grow` replays the
        action from the record alone."""
        return {
            "candidate_uid": int(candidate_uid),
            "best_slope": None if best_slope is None else float(best_slope),
            "span": float(span),
            "stagnation_window": float(self.stagnation_window),
            "stagnation_tol": float(self.stagnation_tol),
            "inner_pop": int(inner_pop),
            "growth_factor": float(self.growth_factor),
            "max_inner_pop": (
                None if self.max_inner_pop is None else int(self.max_inner_pop)
            ),
        }


def grow_evidence(
    ladder: GrowthLadder,
    series_by_uid: dict[int, Any],
    inner_pop: int,
) -> dict[str, Any] | None:
    """Build the grow-consult evidence from per-candidate inner
    best-fitness series (the nested telemetry, repeat-averaged): the
    *most stagnant* candidate — the one whose windowed slope projects the
    least improvement — is the trigger candidate.  Returns ``None`` when
    no candidate has a usable (>= 2 finite points) windowed series.

    ONE definition shared by the solo :class:`~evox_tpu.hpo.HPORunner`
    and the service's per-tenant consult, so both journal identical
    evidence shapes."""
    from ..obs.flight import window_slope

    worst_uid: int | None = None
    worst_slope: float | None = None
    span = 0.0
    window = int(ladder.stagnation_window)
    for uid, series in series_by_uid.items():
        values = [float(v) for v in series]
        tail = values[-(window + 1):]
        rows = [
            {"generation": float(g), "best_fitness": v}
            for g, v in enumerate(tail)
        ]
        slope = window_slope(rows, "best_fitness")
        if slope is None:
            continue
        # Minimizing frame: the largest slope is the least improvement —
        # the most stagnant candidate triggers.
        if worst_slope is None or slope > worst_slope:
            worst_uid, worst_slope = int(uid), float(slope)
            span = float(len(tail) - 1)
    if worst_uid is None:
        return None
    return ladder.evidence(
        candidate_uid=worst_uid,
        best_slope=worst_slope,
        span=span,
        inner_pop=inner_pop,
    )


class HPOGrowPolicy(RestartPolicy):
    """The growth ladder as a :class:`~evox_tpu.resilience.RestartPolicy`:
    riding the runner's restart machinery buys the whole persistence
    contract for free — fired growths are manifest lineage, resume
    replays them via :meth:`rebuild_template`, and the ``max_restarts``
    budget bounds the ladder.

    The outer search state (algorithm + monitor) is preserved untouched;
    only the nested problem sub-state is rebuilt at the grown shape
    (``needs_init=False`` — the next segment simply evaluates the grown
    ladder).  When the triggering
    :class:`~evox_tpu.control.Decision` rode in (``ctx.decision``), its
    action IS the target population (the journaled, replayable value);
    threshold-probe firings (an unhealthy inner state, IPOP's original
    trigger) compute it from the ladder."""

    name = "hpo-grow"

    def __init__(self, ladder: GrowthLadder):
        self.ladder = ladder

    def _graft(self, workflow: Any, grown: NestedProblem) -> None:
        from ..parallel import iter_problem_chain

        nested = find_nested(getattr(workflow, "problem", None))
        if workflow.problem is nested:
            workflow.problem = grown
            return
        for p in iter_problem_chain(workflow.problem):
            if getattr(p, "problem", None) is nested:
                p.problem = grown
                return
        raise ValueError(
            "could not graft the regrown NestedProblem into the workflow's "
            "problem chain"
        )

    def apply(self, ctx: RestartContext):
        nested = find_nested(getattr(ctx.workflow, "problem", None))
        if nested is None:
            raise ValueError(
                f"{self.name} needs a workflow whose problem chain contains "
                f"a NestedProblem"
            )
        current = nested.inner_pop
        new_pop = current
        if ctx.decision is not None and str(ctx.decision.action).isdigit():
            new_pop = int(ctx.decision.action)
        else:
            new_pop = self.ladder.next_pop(current)
        if new_pop <= current:
            # Cap reached: nothing to grow — perturb the inner streams in
            # place so the retry at least explores fresh trajectories
            # (the rollback-in-place degradation).
            state = perturb_prng_keys(
                ctx.state, self.ladder.salt + ctx.restart_index
            )
            return state, ctx.generation, False, {
                "inner_pop": current,
                "grown": False,
            }
        grown = nested.with_inner_pop(new_pop, self.ladder.inner_factory)
        self._graft(ctx.workflow, grown)
        ctx.runner._rebind_workflow()
        prob = grown.regrow_state(
            ctx.state["problem"], self.ladder.salt + ctx.restart_index
        )
        state = ctx.state.replace(problem=prob)
        return state, ctx.generation, False, {
            "inner_pop": new_pop,
            "grown": True,
        }

    def rebuild_template(self, workflow, template, lineage, runner=None):
        events = [
            e
            for e in lineage
            if e.policy == self.name and e.detail.get("grown")
        ]
        if not events or runner is None:
            return template
        nested = find_nested(getattr(workflow, "problem", None))
        if nested is None:
            return template
        import jax

        grown = nested.with_inner_pop(
            int(events[-1].detail["inner_pop"]), self.ladder.inner_factory
        )
        self._graft(workflow, grown)
        runner._rebind_workflow()
        # Only structure (shapes/dtypes/treedef) matters for a template;
        # the key value is irrelevant.
        return template.replace(problem=grown.setup(jax.random.key(0)))
