"""Framework-wide numerics plane: mixed-precision storage policies and
first-class partitionable PRNG key implementations.

The single biggest *measured* raw-speed lever on the north-star PSO bench
(100k x 1000) is not arithmetic — it is bytes and random bits: bf16 state
plus the hardware ``rbg`` generator runs +75% over f32/Threefry, while
bf16 alone is *slower* (BASELINE.md; random-bit generation is the
bottleneck).  Until this package, that win existed only as two hand-built
bench configs; now it is a policy every workflow, runner, service tenant
and HPO nest can opt into:

* :class:`PrecisionPolicy` — bf16/fp16 **storage** leaves with f32
  **compute/reductions**, applied per algorithm through a declarative
  per-leaf dtype map (``Algorithm.storage_leaves``).  The one
  ``promote``/``demote`` seam lives in ``StdWorkflow._step``, so the fused
  segment scan's carry stays in storage dtype (HBM traffic halves) while
  every generation's math runs in the compute dtype.
* :func:`make_key` / :func:`resolve_key_impl` / :func:`coerce_key` — the
  ``key_impl`` knob (``"threefry2x32"`` default, ``"rbg"`` the
  partitionable hardware generator) plumbed through workflow, runner,
  service, and ``bootstrap_fleet``.  ``rbg`` keys compose with the GL006
  topology-invariant ``fold_in`` contract and the service's identity-keyed
  tenant streams: runs are self-consistent per impl (fused==debug,
  solo==packed, resume==uninterrupted), and cross-impl divergence is
  documented and gated, never accidental.
* :func:`check_precision` — the checkpoint-manifest guard: a bf16
  checkpoint refuses to silently load as f32 and vice versa
  (:class:`~evox_tpu.utils.checkpoint.CheckpointError`, remesh-style).

Policy identity is folded into ``TenantSpec.bucket_key``, checkpoint
manifests, and the persistent executable-cache signature, so two runs
differing only in numerics can never share a compiled program, a bucket,
or a resume point by accident.  See ``docs/guide/precision.md``.
"""

from .policy import (
    DEFAULT_PRECISION_TAG,
    PrecisionPolicy,
    check_precision,
    precision_identity,
    precision_tag,
)
from .prng import (
    KEY_IMPLS,
    coerce_key,
    key_impl_name,
    make_key,
    state_key_impl,
    resolve_key_impl,
)

__all__ = [
    "PrecisionPolicy",
    "precision_identity",
    "precision_tag",
    "check_precision",
    "DEFAULT_PRECISION_TAG",
    "KEY_IMPLS",
    "make_key",
    "coerce_key",
    "key_impl_name",
    "state_key_impl",
    "resolve_key_impl",
]
