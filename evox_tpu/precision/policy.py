"""Mixed-precision storage policy.

The policy model separates two dtypes:

* **storage** — what a state leaf is *carried* as between generations:
  the dtype of the fused segment scan's carry, of checkpoint archives, and
  of the HBM-resident state on the per-step path.  ``bfloat16`` halves the
  bytes of every mapped leaf.
* **compute** — what one generation's math runs in.  The workflow's step
  seam promotes mapped leaves to the compute dtype on entry and demotes
  them back on exit, so reductions, best-fold comparisons and the
  algorithm's update arithmetic never accumulate in the narrow type.

Which leaves are mapped is **per-algorithm and declarative**: an algorithm
opts in by declaring ``storage_leaves`` — a tuple of state-leaf names (or
a ``{name: dtype}`` map for per-leaf overrides) naming the
population-sized buffers that are safe to narrow.  Small accumulating
leaves (a CMA-ES covariance, an Adam moment) stay out of the map and keep
full precision.  Applying a policy to an algorithm with no declaration
raises — narrowing state a class author never audited is how convergence
silently degrades.

Identity discipline: :func:`precision_identity` (a hashable tuple) rides
in ``TenantSpec.bucket_key`` and the executable-cache signature;
:func:`precision_tag` (a string) rides in checkpoint manifests, where
:func:`check_precision` enforces the no-silent-crossing rule
(``CheckpointError``, structured like the remesh topology guard).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping

import jax
import jax.numpy as jnp

__all__ = [
    "PrecisionPolicy",
    "precision_identity",
    "precision_tag",
    "check_precision",
    "DEFAULT_PRECISION_TAG",
]

# The tag an archive without a precision entry (or a policy-less run) is
# treated as: full-precision storage, identical compute.
DEFAULT_PRECISION_TAG = "storage=float32,compute=float32"

_STORAGE_DTYPES = ("bfloat16", "float16", "float32")
_COMPUTE_DTYPES = ("float32", "float64")


@dataclass(frozen=True)
class PrecisionPolicy:
    """Declarative mixed-precision policy: ``storage`` dtype for the
    algorithm's mapped state leaves, ``compute`` dtype for the step's math.

    :param storage: dtype name the mapped leaves are carried as between
        generations (``"bfloat16"`` — the TPU-native narrow type — or
        ``"float16"``; ``"float32"`` makes the policy an identity).
    :param compute: dtype name one generation's arithmetic runs in
        (``"float32"`` default; reductions and best-folds happen here).
    :param leaves: optional explicit per-leaf map overriding the
        algorithm's ``storage_leaves`` declaration — a tuple of leaf
        names (all stored as ``storage``) or a ``{name: dtype}`` mapping.
        Leave ``None`` to use the algorithm's own declaration (the normal,
        author-audited path).
    """

    storage: str = "bfloat16"
    compute: str = "float32"
    leaves: tuple = None  # tuple[str, ...] | tuple[tuple[str, str], ...]

    def __post_init__(self) -> None:
        if self.storage not in _STORAGE_DTYPES:
            raise ValueError(
                f"storage must be one of {_STORAGE_DTYPES}, got "
                f"{self.storage!r}"
            )
        if self.compute not in _COMPUTE_DTYPES:
            raise ValueError(
                f"compute must be one of {_COMPUTE_DTYPES}, got "
                f"{self.compute!r}"
            )
        if self.leaves is not None:
            # Normalize {name: dtype} / iterables to a canonical, hashable
            # sorted tuple of (name, dtype) pairs so policy identity (and
            # therefore bucket keys) never depends on declaration order.
            if isinstance(self.leaves, Mapping):
                pairs = tuple(
                    sorted((str(k), str(v)) for k, v in self.leaves.items())
                )
            else:
                pairs = tuple(
                    sorted(
                        (str(leaf), self.storage)
                        if isinstance(leaf, str)
                        else (str(leaf[0]), str(leaf[1]))
                        for leaf in self.leaves
                    )
                )
            for _, dt in pairs:
                if dt not in _STORAGE_DTYPES:
                    raise ValueError(
                        f"per-leaf storage dtype must be one of "
                        f"{_STORAGE_DTYPES}, got {dt!r}"
                    )
            object.__setattr__(self, "leaves", pairs)

    # -- dtype handles ------------------------------------------------------
    @property
    def storage_dtype(self):
        return jnp.dtype(self.storage)

    @property
    def compute_dtype(self):
        return jnp.dtype(self.compute)

    # -- per-algorithm leaf map --------------------------------------------
    def leaf_map(self, algorithm: Any) -> dict[str, Any]:
        """The ``{leaf_name: storage_dtype}`` map this policy applies to
        ``algorithm``'s state.  Explicit ``leaves`` win; otherwise the
        algorithm's declarative ``storage_leaves`` attribute is consulted.
        Raises ``TypeError`` when neither exists — precision is opt-in per
        algorithm, never inferred."""
        if self.leaves is not None:
            return {name: jnp.dtype(dt) for name, dt in self.leaves}
        declared = getattr(algorithm, "storage_leaves", None)
        if declared is None:
            raise TypeError(
                f"{type(algorithm).__name__} declares no `storage_leaves` "
                f"map, so a PrecisionPolicy cannot be applied to it: narrow "
                f"storage is opt-in per algorithm (declare the class "
                f"attribute naming the population-sized leaves that are "
                f"safe to store narrow, or pass PrecisionPolicy(leaves=...) "
                f"to override explicitly)"
            )
        if isinstance(declared, Mapping):
            return {str(k): jnp.dtype(str(v)) for k, v in declared.items()}
        return {str(name): self.storage_dtype for name in declared}

    def validate_state(self, algo_state: Any, leaf_map: Mapping[str, Any]) -> None:
        """Refuse a map naming leaves the state does not have.  A typo'd
        entry (``PrecisionPolicy(leaves=("velocty",))``) or a stale
        ``storage_leaves`` declaration would otherwise be a silent no-op:
        the run executes at full precision while its bucket key,
        exec-cache signature, and checkpoint manifest all record the
        narrow policy — a mislabeled measurement, the exact failure class
        this plane's loud guards exist to prevent."""
        missing = sorted(set(leaf_map) - set(algo_state))
        if missing:
            raise ValueError(
                f"PrecisionPolicy maps state leaves {missing} that do not "
                f"exist in the algorithm state (leaves: "
                f"{sorted(algo_state)}): a misnamed entry would silently "
                f"run at full precision under a narrow-policy identity — "
                f"fix the leaves= map or the storage_leaves declaration"
            )

    # -- the cast seam ------------------------------------------------------
    def _cast(self, state: Any, target_of) -> Any:
        """Cast mapped leaves of a flat algorithm ``State`` via
        ``target_of(leaf_name) -> dtype | None`` (None = leave alone).
        PRNG keys and non-floating leaves are never touched."""
        updates = {}
        for name in state:
            dtype = target_of(name)
            if dtype is None:
                continue
            leaf = state[name]
            if not isinstance(leaf, jax.Array) and not hasattr(leaf, "dtype"):
                continue
            if jax.dtypes.issubdtype(leaf.dtype, jax.dtypes.prng_key):
                continue
            if not jnp.issubdtype(leaf.dtype, jnp.floating):
                continue
            if leaf.dtype != dtype:
                updates[name] = leaf.astype(dtype)
        return state.replace(**updates) if updates else state

    def demote(self, algo_state: Any, leaf_map: Mapping[str, Any]) -> Any:
        """Storage form: mapped leaves narrowed to their storage dtype —
        the dtype the scan carry, checkpoints, and HBM-resident state
        hold between generations."""
        return self._cast(algo_state, leaf_map.get)

    def promote(self, algo_state: Any, leaf_map: Mapping[str, Any]) -> Any:
        """Compute form: mapped leaves widened to the compute dtype for
        one generation's math."""
        compute = self.compute_dtype
        return self._cast(
            algo_state, lambda name: compute if name in leaf_map else None
        )

    # -- identity -----------------------------------------------------------
    def identity(self) -> tuple:
        """Hashable identity of this policy — what bucket keys and the
        executable-cache signature fold in."""
        return ("precision", self.storage, self.compute, self.leaves)

    def tag(self) -> str:
        """Manifest form of the identity (human-greppable string)."""
        base = f"storage={self.storage},compute={self.compute}"
        if self.leaves is not None:
            base += ",leaves=" + ";".join(f"{n}:{d}" for n, d in self.leaves)
        return base


def precision_identity(policy: PrecisionPolicy | None) -> tuple:
    """Bucket-key / cache-signature identity, total over ``None`` (the
    policy-less default is full precision)."""
    if policy is None:
        return ("precision", "float32", "float32", None)
    return policy.identity()


def precision_tag(policy: PrecisionPolicy | None) -> str:
    """Checkpoint-manifest tag, total over ``None``."""
    return DEFAULT_PRECISION_TAG if policy is None else policy.tag()


def check_precision(
    manifest_tag: str | None,
    policy: PrecisionPolicy | None,
    *,
    context: str = "checkpoint",
) -> None:
    """The manifest guard: refuse to load a checkpoint across a precision
    boundary.  ``manifest_tag`` is the archive's recorded ``precision``
    entry (``None`` for archives predating the plane — treated as full
    precision, exactly what a policy-less writer produced); ``policy`` is
    what the loading run is configured with.

    Raises :class:`~evox_tpu.utils.checkpoint.CheckpointError` on any
    mismatch — a bf16 archive silently widened into an f32 run (or an f32
    archive silently narrowed into a bf16 run) would *load cleanly* under
    the generic same-kind dtype casting and corrupt the run's numerics
    story instead of failing loudly, the same class of bug the remesh
    topology guard exists for."""
    from ..utils.checkpoint import CheckpointError

    recorded = manifest_tag if manifest_tag else DEFAULT_PRECISION_TAG
    expected = precision_tag(policy)
    if recorded != expected:
        raise CheckpointError(
            f"{context}: precision policy mismatch — the archive was "
            f"written under [{recorded}] but this run is configured for "
            f"[{expected}]. A checkpoint never crosses a precision "
            f"boundary silently: load it with the matching "
            f"PrecisionPolicy, or re-seed the run."
        )
