"""First-class PRNG key implementations (the ``key_impl`` knob).

JAX's default Threefry generator derives every random word through a long
per-word ALU chain on the VPU; at north-star shapes (2 x pop x dim ~= 200M
words per PSO generation) that chain — not the swarm arithmetic — is the
step's bottleneck (BASELINE.md: bf16+rbg 242 gen/s vs 138 f32/threefry,
while bf16 alone is *slower*).  The ``rbg`` implementation uses the TPU's
hardware random-bit generator and is **partitionable**: under ``vmap`` /
``shard_map`` the per-lane draws need no per-word key derivation, which is
exactly why it is the sharding-friendly choice.

The trade, stated once and gated by tests rather than discovered in
production:

* **Within one impl, determinism is full-strength.**  ``fold_in`` /
  ``split`` are defined for every impl, so the GL006 topology-invariant
  folding contract and the service's identity-keyed tenant streams hold
  unchanged: fused == debug, solo == packed, resume == uninterrupted —
  bit-identical per impl (``tests/test_precision.py`` pins the matrix).
* **Across impls, streams differ by construction.**  A threefry run and an
  rbg run of the same seed draw different numbers; that divergence is
  documented here and *gated* — checkpoint manifests record the key impl,
  bucket keys split on it, and :func:`coerce_key` makes any cross-impl
  key handoff an explicit, deterministic re-seeding instead of a silent
  reinterpretation.

``resolve_key_impl`` honors the ``EVOX_TPU_KEY_IMPL`` environment variable
so a whole fleet can be flipped without touching call sites
(:func:`~evox_tpu.parallel.bootstrap_fleet` plumbs the same knob
process-wide).
"""

from __future__ import annotations

import os
import re

import jax
import jax.numpy as jnp

__all__ = [
    "KEY_IMPLS",
    "resolve_key_impl",
    "make_key",
    "coerce_key",
    "key_impl_name",
    "state_key_impl",
]

# The built-in jax implementations this library supports.  "rbg" is the
# partitionable hardware generator; "unsafe_rbg" additionally relaxes
# fold_in/split derivation quality for maximum throughput (only for runs
# that never rely on derived-stream independence).
KEY_IMPLS = ("threefry2x32", "rbg", "unsafe_rbg")

DEFAULT_KEY_IMPL = "threefry2x32"

_ENV_KEY_IMPL = "EVOX_TPU_KEY_IMPL"


def resolve_key_impl(impl: str | None) -> str:
    """Canonical impl name for a knob value: explicit argument first, then
    the ``EVOX_TPU_KEY_IMPL`` environment variable, then the library
    default (Threefry — bit-compatible with every pre-plane run)."""
    name = impl or os.environ.get(_ENV_KEY_IMPL) or DEFAULT_KEY_IMPL
    if name not in KEY_IMPLS:
        raise ValueError(
            f"unknown PRNG key impl {name!r}; expected one of {KEY_IMPLS}"
        )
    return name


def make_key(seed: int, impl: str | None = None) -> jax.Array:
    """A typed PRNG key of the resolved implementation — the one
    constructor every key-creating seam in the library routes through."""
    return jax.random.key(int(seed), impl=resolve_key_impl(impl))


def key_impl_name(key: jax.Array) -> str:
    """The implementation name of a typed key (``"threefry2x32"`` /
    ``"rbg"`` / ...), robust across jax's PRNGSpec repr variants."""
    spec = jax.random.key_impl(key)
    name = getattr(spec, "name", None)
    if isinstance(name, str) and name:
        return name
    # PRNGSpec.__repr__ is the stable public surface on jax 0.4.x
    # (repr(spec) == "'rbg'"); strip the quoting.
    return re.sub(r"""^['"]|['"]$""", "", repr(spec))


def state_key_impl(state) -> str | None:
    """The key implementation a state pytree ACTUALLY carries — the impl
    of its first typed PRNG leaf (tree order), or ``None`` when no typed
    key leaf exists.  This is what checkpoint manifests must record: a
    knob-less workflow (``key_impl=None``, pass-through semantics) can
    legitimately run on whatever impl the caller's key was, and recording
    the resolved *default* there would make the cross-impl resume guard
    fire falsely on exactly those archives."""
    for leaf in jax.tree_util.tree_leaves(state):
        if jax.dtypes.issubdtype(
            getattr(leaf, "dtype", None), jax.dtypes.prng_key
        ):
            return key_impl_name(leaf)
    return None


def coerce_key(key_or_seed, impl: str | None = None) -> jax.Array:
    """Deterministically produce a key of the requested implementation.

    * an ``int`` seed builds a fresh key of the impl;
    * a key already of the impl passes through unchanged (the common case
      — zero-cost when callers already agree);
    * a key of a *different* impl is re-seeded by folding its raw key-data
      words, in order, into a zero key of the target impl — deterministic
      and total, so template-building code paths (restart rebuilds,
      service resume templates) can hand any key to a workflow with a
      pinned ``key_impl`` and always land on the same stream.

    The cross-impl branch is an explicit re-seeding, not a
    reinterpretation: there is no meaning-preserving conversion between
    generators, and pretending otherwise is how cross-impl divergence
    becomes accidental instead of documented."""
    target = resolve_key_impl(impl)
    if not isinstance(key_or_seed, jax.Array) or not jax.dtypes.issubdtype(
        getattr(key_or_seed, "dtype", None), jax.dtypes.prng_key
    ):
        if getattr(key_or_seed, "ndim", 0):
            # A legacy RAW key array (`jax.random.PRNGKey(0)`): pre-plane
            # code accepted these everywhere, so wrap the bits back into
            # a typed key and fall through to the normal cross-impl
            # handling instead of dying in `int()` of a length-2 array.
            # Raw buffers carry no impl tag, and wrap_key_data's default
            # follows the PROCESS default impl — which bootstrap_fleet
            # may have flipped — so dispatch on the trailing word count
            # instead: threefry raw keys are (2,) uint32, rbg-family
            # (4,).  Deterministic either way; the fold below only
            # consumes the bits.
            raw = jnp.asarray(key_or_seed, jnp.uint32)
            key_or_seed = jax.random.wrap_key_data(
                raw, impl="threefry2x32" if raw.shape[-1] == 2 else "rbg"
            )
        else:
            return make_key(int(key_or_seed), target)
    if key_impl_name(key_or_seed) == target:
        return key_or_seed
    out = jax.random.key(0, impl=target)
    for word in jnp.ravel(jax.random.key_data(key_or_seed)):
        out = jax.random.fold_in(out, word.astype(jnp.uint32))
    return out
