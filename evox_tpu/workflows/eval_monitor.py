"""Evaluation monitor.

TPU-native counterpart of the reference ``EvalMonitor``
(``src/evox/workflows/eval_monitor.py:83-378``): tracks the latest
solution/fitness and a running top-k *inside* jitted code as pure State, and
streams full fitness/solution/auxiliary history to host memory.

The reference escapes the compiled graph with a custom op ``_data_sink``
chained through a token tensor to force ordering
(``eval_monitor.py:46-80,243-251``).  Here the same side channel is
``jax.experimental.io_callback(ordered=True)`` — the JAX effects system plays
the token's role.  For vmapped (batched-instance) workflows pass
``ordered=False`` and ``num_instances=N``: JAX's batching rule for unordered
``io_callback`` emits one host call per batch element, and — because
*unordered* callbacks are explicitly allowed to arrive in any order under
async dispatch — every payload carries an explicit ``(generation,
instance_id)`` tag that the host-side accessors re-sort by.  Arrival order is
never trusted.  Instance ids are assigned by ``StdWorkflow.init(key,
instance_id=...)`` (e.g. ``jax.vmap(wf.init)(keys, jnp.arange(N))``); without
them, entries are grouped by generation tag only (arrival order within a
generation), which is only safe on effectively-synchronous backends.
"""

from __future__ import annotations

import warnings
import weakref
from enum import IntEnum
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback

from ..core import Monitor, State

__all__ = ["EvalMonitor"]


class HistoryType(IntEnum):
    FITNESS = 0
    SOLUTION = 1
    AUXILIARY = 2


# Host-side history store: monitor id -> {HistoryType: [np.ndarray, ...]}
# (reference: module-global ``__monitor_history__``, ``eval_monitor.py:46``).
__monitor_history__: dict[int, dict[int, list]] = {}


class EvalMonitor(Monitor):
    """Monitor hooked around evaluation; records offspring, fitness, top-k
    elites, and (on demand) the full history / pareto front.

    **Single-owner contract.** One ``EvalMonitor`` instance serves ONE
    workflow: host-side history is keyed by the monitor's object identity,
    and ``StdWorkflow.__init__`` writes ``opt_direction`` (and
    ``record_auxiliary`` writes ``aux_keys``) onto the instance.  Attaching
    the same instance to a second workflow interleaves both runs' histories
    under one key and overwrites the first workflow's config — construct a
    fresh monitor per workflow instead.  (vmapping ONE workflow over
    stacked instances is fine: that is what ``ordered=False`` +
    ``num_instances`` exist for.)"""

    def __init__(
        self,
        multi_obj: bool = False,
        full_fit_history: bool = True,
        full_sol_history: bool = False,
        full_pop_history: bool = False,
        topk: int = 1,
        ordered: bool = True,
        num_instances: int | None = None,
    ):
        """
        :param multi_obj: whether the optimization is multi-objective.
        :param full_fit_history: record full fitness history on the host.
        :param full_sol_history: record full solution history on the host.
        :param full_pop_history: record auxiliary population records fed via
            ``record_auxiliary``.
        :param topk: number of elite solutions tracked (single-objective).
        :param ordered: use ordered host callbacks; set False when the
            workflow is vmapped over instances (ordered callbacks cannot be
            vmapped).
        :param num_instances: with ``ordered=False`` under a vmapped
            workflow, the instance count; history entries are re-grouped so
            each carries a leading ``(num_instances,)`` axis.
        """
        self.multi_obj = multi_obj
        self.full_fit_history = full_fit_history
        self.full_sol_history = full_sol_history
        self.full_pop_history = full_pop_history
        self.topk = topk
        self.ordered = ordered
        self.num_instances = num_instances
        self.opt_direction = 1
        self.aux_keys: list[str] = []
        self._id_ = id(self)
        __monitor_history__[self._id_] = {t: [] for t in HistoryType}
        weakref.finalize(self, __monitor_history__.pop, self._id_, None)

    # Fused-segment capture redirection (see ``Monitor._capture`` in
    # ``core/components.py``): while a workflow traces a fused multi-
    # generation segment, ``_capture`` is a list and ``_sink`` appends the
    # traced payload instead of emitting an ``io_callback`` — a host
    # round-trip per generation inside a ``lax.scan`` would stall the
    # device loop, which is exactly what fusing exists to avoid.  The
    # batched payloads come back as segment telemetry and are ingested at
    # the boundary by :meth:`ingest_sinks`.
    _capture: list | None = None

    # -- config ------------------------------------------------------------
    def set_config(self, **config: Any) -> "EvalMonitor":
        for k in ("multi_obj", "full_fit_history", "full_sol_history", "topk", "opt_direction", "ordered", "num_instances"):
            if k in config:
                setattr(self, k, config[k])
        return self

    # -- state -------------------------------------------------------------
    def setup(self, key: jax.Array) -> State:
        del key
        empty = jnp.empty((0,))
        return State(
            latest_solution=empty,
            latest_fitness=empty,
            topk_solutions=empty,
            topk_fitness=empty,
            generation=jnp.int32(0),
            # Instance label for history tagging; assigned by
            # ``StdWorkflow.setup(key, instance_id=...)`` when vmapping.
            instance_id=jnp.int32(-1),
            # Cumulative count of individuals whose fitness came back
            # non-finite and was quarantined by the workflow
            # (``StdWorkflow(quarantine_nonfinite=True)``).
            num_nonfinite=jnp.int32(0),
            # Cumulative count of shard-quarantine events: one per (mesh
            # shard, evaluation) whose whole row block was penalized
            # (``StdWorkflow(quarantine_granularity="shard")``).
            num_shard_quarantines=jnp.int32(0),
            # Automatic restarts applied to this run by a supervising
            # ``ResilientRunner`` health/restart policy.
            num_restarts=jnp.int32(0),
            # Graceful preemptions (SIGTERM / maintenance events) this run
            # has survived — bumped into the emergency checkpoint's state
            # by ``PreemptionGuard``-aware supervisors, so the count rides
            # every resume.
            num_preemptions=jnp.int32(0),
        )

    # -- host side channel --------------------------------------------------
    def _sink(self, data: jax.Array, data_type: int, state: State, slot: int = 0) -> None:
        """Stream ``data`` to host history, tagged ``(generation, instance,
        slot)`` so accessors can re-sort: unordered callbacks carry no
        delivery-order guarantee (see module docstring)."""
        if self._capture is not None:
            # Fused segment trace: hand the traced payload (plus its static
            # site identity) to the workflow instead of crossing to the
            # host — the scan batches it per generation and the boundary
            # flush (``ingest_sinks``) appends it with identical tags and
            # ordering to what the callback path would have produced.
            self._capture.append(
                (int(data_type), slot, data, state.generation, state.instance_id)
            )
            return

        def append(x, gen, inst):
            __monitor_history__[self._id_][int(data_type)].append(
                (int(gen), int(inst), slot, np.asarray(x))
            )

        # An ordered callback runs on a single device by construction; pin
        # its sharding explicitly — without the pin, XLA's SPMD sharding
        # propagation hard-aborts (Check failed, jax 0.4.x) when the callback
        # custom-call shares a program with shard_map partitioning (the
        # distributed fused-run path).
        kwargs = {}
        if self.ordered:
            kwargs["sharding"] = jax.sharding.SingleDeviceSharding(
                jax.local_devices()[0]
            )
        io_callback(
            append, None, data, state.generation, state.instance_id,
            ordered=self.ordered, **kwargs,
        )

    # -- hooks --------------------------------------------------------------
    def post_ask(self, state: State, population: jax.Array) -> State:
        return state.replace(latest_solution=population)

    def pre_tell(self, state: State, fitness: jax.Array) -> State:
        state = state.replace(
            latest_fitness=fitness, generation=state.generation + 1
        )
        if fitness.ndim == 1:
            # Single-objective: maintain running top-k. The first call (empty
            # placeholder state) and later calls are separate traces, so the
            # shape switch below is a static Python branch.
            if fitness.shape[0] < self.topk:
                raise ValueError(
                    f"EvalMonitor(topk={self.topk}) needs at least topk "
                    f"fitness values per generation, got a population of "
                    f"{fitness.shape[0]}"
                )
            if state.topk_solutions.ndim <= 1:
                cand_solutions = state.latest_solution
                cand_fitness = fitness
            else:
                cand_solutions = jnp.concatenate(
                    [state.topk_solutions, state.latest_solution]
                )
                cand_fitness = jnp.concatenate([state.topk_fitness, fitness])
            _, rank = jax.lax.top_k(-cand_fitness, self.topk)
            state = state.replace(
                topk_fitness=cand_fitness[rank],
                topk_solutions=cand_solutions[rank],
            )
        elif fitness.ndim != 2:
            raise ValueError(f"Invalid fitness shape: {fitness.shape}")
        # Multi-objective: no single top-k; the pareto front is recovered from
        # history on demand (``get_pf``).
        if self.full_sol_history:
            self._sink(state.latest_solution, HistoryType.SOLUTION, state)
        if self.full_fit_history:
            self._sink(fitness, HistoryType.FITNESS, state)
        return state

    def record_history(self, state: State) -> State:
        """Manually flush the latest solution/fitness to host history
        (reference ``eval_monitor.py:243-251``; the automatic path does this
        inside :meth:`pre_tell`)."""
        if self.full_sol_history:
            self._sink(state.latest_solution, HistoryType.SOLUTION, state)
        if self.full_fit_history:
            self._sink(state.latest_fitness, HistoryType.FITNESS, state)
        return state

    def record_nonfinite(self, state: State, mask: jax.Array) -> State:
        """Count quarantined individuals (non-finite fitness rows replaced
        by the workflow's worst-case penalty) into the cumulative
        ``num_nonfinite`` metric.  ``mask`` is the per-individual boolean
        quarantine mask for this evaluation."""
        if "num_nonfinite" not in state:
            # States restored from pre-metric checkpoints (allow_missing
            # pathways) or handed in by custom setups may lack the counter.
            return state
        return state.replace(
            num_nonfinite=state.num_nonfinite
            + jnp.sum(mask, dtype=jnp.int32)
        )

    def record_shard_quarantine(self, state: State, shard_mask: jax.Array) -> State:
        """Count shard-quarantine events (whole mesh shards penalized by the
        workflow's shard-granular non-finite quarantine) into the cumulative
        ``num_shard_quarantines`` metric.  ``shard_mask`` is the per-shard
        boolean mask for this evaluation — each ``True`` entry is one
        event."""
        if "num_shard_quarantines" not in state:
            # Pre-metric checkpoints / custom setups may lack the counter.
            return state
        return state.replace(
            num_shard_quarantines=state.num_shard_quarantines
            + jnp.sum(shard_mask, dtype=jnp.int32)
        )

    def record_restart(self, state: State) -> State:
        """Count an automatic restart (fired by a supervising
        ``ResilientRunner`` restart policy) into the cumulative
        ``num_restarts`` metric.  Runs on the host between jitted chunks —
        the counter lives in the monitor state, so it is checkpointed and
        survives kill-and-resume with the rest of the run."""
        if "num_restarts" not in state:
            # Pre-metric checkpoints / custom setups may lack the counter.
            return state
        return state.replace(num_restarts=state.num_restarts + 1)

    def record_preemption(self, state: State) -> State:
        """Count a graceful preemption (SIGTERM / maintenance event caught
        by a supervising ``PreemptionGuard``) into the cumulative
        ``num_preemptions`` metric.  Runs on the host at the tripping
        boundary, immediately before the emergency checkpoint is written —
        so the counter the resumed run restores already includes the
        preemption that created its checkpoint."""
        if "num_preemptions" not in state:
            # Pre-metric checkpoints / custom setups may lack the counter.
            return state
        return state.replace(num_preemptions=state.num_preemptions + 1)

    def record_auxiliary(self, state: State, aux: dict[str, jax.Array]) -> State:
        if self.full_pop_history:
            if not self.aux_keys:
                # Deliberate trace-time capture, not per-generation state:
                # the aux slot order is static config discovered on the first
                # trace (record_step returns the same keys every generation),
                # and the host-side history accessors need it after the run.
                self.aux_keys = list(aux.keys())  # graftlint: disable=GL005
            for slot, k in enumerate(self.aux_keys):
                self._sink(aux[k], HistoryType.AUXILIARY, state, slot=slot)
        return state

    def ingest_sinks(self, meta, sinks, executed, lane: int | None = None) -> None:
        """Boundary flush of a fused segment's captured sink batches into
        the host-side history (the batched counterpart of the per-
        generation ``io_callback`` path — one call per *segment* instead of
        one host round-trip per generation).

        :param meta: ``[(history_type, slot), ...]`` — one static site
            descriptor per sink call the traced step performs, in program
            order (recorded at trace time by the workflow).
        :param sinks: ``[(data, generations, instances), ...]`` matching
            ``meta``; each array carries a leading ``(n_generations,)``
            axis — or ``(n_instances, n_generations, ...)`` for a vmapped
            segment.
        :param executed: how many of the batched generations actually ran
            (a fused segment may stop early on an unhealthy state); scalar,
            or ``(n_instances,)`` for vmapped segments.  Rows past it are
            padding and are dropped.
        :param lane: demux mode — ingest ONLY the given instance-axis row
            of a vmapped pack's telemetry into *this* monitor, as if the
            lane had run solo.  This is how a multi-tenant pack
            (``evox_tpu.service.TenantPack``) routes one compiled
            segment's interleaved telemetry to each tenant's own monitor:
            one ``ingest_sinks(..., lane=i)`` call per occupied lane, each
            on that tenant's monitor instance.  Tags (generation,
            instance id) come from the lane's own payload rows, so the
            resulting history is entry-for-entry what the tenant's solo
            run would have recorded.

        Entries are appended per generation in site program order, so the
        resulting history is element-for-element what the ``ordered=True``
        callback path records; tags are taken from the batched payload, so
        the unordered accessors' re-sort semantics hold too.  Call once per
        successfully executed segment (the supervising runner does) —
        re-ingesting the same telemetry duplicates entries exactly like a
        replayed callback would."""
        hist = __monitor_history__[self._id_]
        executed = np.asarray(executed)
        if lane is not None:
            if executed.ndim == 0:
                raise ValueError(
                    "ingest_sinks(lane=...) demuxes a VMAPPED pack's "
                    "telemetry (leading instance axis); this telemetry is "
                    "unbatched — ingest it directly"
                )
            lane = int(lane)
            executed = executed[lane]
            sinks = [
                tuple(np.asarray(x)[lane] for x in site) for site in sinks
            ]
        if executed.ndim == 0:
            for g in range(int(executed)):
                for (data_type, slot), (data, gens, insts) in zip(meta, sinks):
                    hist[int(data_type)].append(
                        (int(gens[g]), int(insts[g]), slot, np.asarray(data[g]))
                    )
            return
        # Vmapped segment: a leading instance axis on every batch.
        for b in range(executed.shape[0]):
            for g in range(int(executed[b])):
                for (data_type, slot), (data, gens, insts) in zip(meta, sinks):
                    hist[int(data_type)].append(
                        (
                            int(gens[b, g]),
                            int(insts[b, g]),
                            slot,
                            np.asarray(data[b, g]),
                        )
                    )

    # -- history accessors (host side) --------------------------------------
    def _grouped(self, entries: list) -> list:
        """Entries are ``(generation, instance, slot, array)`` tuples in
        arrival order.

        ``ordered=True``: the JAX effects system guarantees arrival order ==
        program order, so entries are returned as they arrived (this also
        keeps sequential re-runs of a reused monitor appended end-to-end).

        ``ordered=False``: unordered callbacks may be delivered in any order,
        so entries are re-sorted by their ``(generation, instance)`` payload
        tags, then (``num_instances=N``) each generation's ``N`` per-instance
        entries are stacked into one batched array.  A reused monitor must be
        ``clear_history()``-ed between runs — duplicate tags are detected and
        raise rather than silently mis-grouping."""
        if self.ordered:
            return [arr for (_, _, _, arr) in entries]
        n = self.num_instances
        # Untagged entries (instance_id=-1, workflow init'ed without ids)
        # can't be distinguished — they fall through to the stable-sort
        # fallback below and are exempt from the duplicate check.
        tags = [(g, i) for (g, i, _, _) in entries if i != -1]
        if len(set(tags)) != len(tags):
            raise RuntimeError(
                "duplicate (generation, instance) history tags — this "
                "monitor recorded more than one run; call clear_history() "
                "(or use a fresh monitor) between unordered/vmapped runs"
            )
        # Stable sort: entries without instance ids (-1) keep arrival order
        # within a generation.
        entries = sorted(entries, key=lambda e: (e[0], e[1]))
        if not n or n <= 1:
            return [arr for (_, _, _, arr) in entries]
        assert len(entries) % n == 0, (
            f"history has {len(entries)} entries, not a multiple of "
            f"num_instances={n} — was the workflow actually vmapped over "
            f"{n} instances?"
        )
        return [
            np.stack([arr for (_, _, _, arr) in entries[i : i + n]])
            for i in range(0, len(entries), n)
        ]

    @property
    def fitness_history(self) -> list:
        """Per-generation fitness arrays from the host-side history
        (``fit_history`` is the reference-parity alias)."""
        return self._grouped(__monitor_history__[self._id_][HistoryType.FITNESS])

    fit_history = fitness_history

    @property
    def solution_history(self) -> list:
        """Per-generation solution arrays from the host-side history
        (requires ``full_sol_history``; ``sol_history`` is the alias)."""
        return self._grouped(__monitor_history__[self._id_][HistoryType.SOLUTION])

    sol_history = solution_history

    @property
    def auxiliary_history(self) -> dict[str, list]:
        """Per-key lists of per-generation auxiliary records (from
        ``Algorithm.record_step``); ``aux_history`` is the alias."""
        raw = __monitor_history__[self._id_][HistoryType.AUXILIARY]
        if not self.aux_keys:
            return {}
        # De-interleave by the slot tag (one slot per aux key), then group
        # each slot's entries by generation/instance like the main histories.
        return {
            k: self._grouped([e for e in raw if e[2] == slot])
            for slot, k in enumerate(self.aux_keys)
        }

    aux_history = auxiliary_history

    def clear_history(self) -> None:
        """Drop this monitor's host-side history (state-side top-k and
        latest-generation buffers are untouched)."""
        __monitor_history__[self._id_] = {t: [] for t in HistoryType}

    def truncate_history(self, generation: int) -> None:
        """Drop host-side history entries tagged PAST ``generation`` —
        rollback support: a run restarted from an earlier checkpoint
        replays those generations, and without pruning the stale entries
        the replay's re-ingested tags would collide with them (the
        unordered accessors detect duplicate ``(generation, instance)``
        tags and raise rather than mis-group).  Entries at or before the
        rollback generation are exactly the ones the restored state's
        trajectory already produced, so they stay."""
        hist = __monitor_history__[self._id_]
        for data_type in list(hist):
            hist[data_type] = [
                e for e in hist[data_type] if e[0] <= generation
            ]

    # -- result accessors ----------------------------------------------------
    def get_latest_fitness(self, state: State) -> jax.Array:
        """Fitness of the latest generation (original sign restored)."""
        return self.opt_direction * state.latest_fitness

    def get_latest_solution(self, state: State) -> jax.Array:
        """Population of the latest generation (pre-transform solutions)."""
        return state.latest_solution

    def get_num_nonfinite(self, state: State) -> jax.Array:
        """Cumulative count of individuals quarantined for non-finite
        fitness (requires ``StdWorkflow(quarantine_nonfinite=True)``, the
        default)."""
        return state.num_nonfinite

    def get_num_shard_quarantines(self, state: State) -> jax.Array:
        """Cumulative count of shard-quarantine events — one per (mesh
        shard, evaluation) whose entire row block was penalized (requires
        ``StdWorkflow(quarantine_granularity="shard")`` on a distributed
        run; 0 otherwise)."""
        return state.num_shard_quarantines

    def get_num_restarts(self, state: State) -> jax.Array:
        """Cumulative count of automatic restarts applied to this run by a
        supervising ``ResilientRunner`` restart policy (0 for unsupervised
        runs)."""
        return state.num_restarts

    def get_num_preemptions(self, state: State) -> jax.Array:
        """Cumulative count of graceful preemptions (SIGTERM / maintenance
        events) this run has survived under a
        ``ResilientRunner(preemption=...)`` supervisor (0 for unsupervised
        or never-preempted runs)."""
        return state.num_preemptions

    def get_topk_fitness(self, state: State) -> jax.Array:
        """Best ``topk`` fitness values so far (original sign restored)."""
        return self.opt_direction * state.topk_fitness

    def get_topk_solutions(self, state: State) -> jax.Array:
        """Solutions achieving the best ``topk`` fitness values so far
        (single-objective only)."""
        self._assert_single("get_topk_solutions")
        return state.topk_solutions

    def get_best_solution(self, state: State) -> jax.Array:
        """The single best solution so far (single-objective only)."""
        self._assert_single("get_best_solution")
        return state.topk_solutions[0]

    def get_best_fitness(self, state: State) -> jax.Array:
        """The single best fitness so far (single-objective only; original
        sign restored)."""
        self._assert_single("get_best_fitness")
        return self.opt_direction * state.topk_fitness[0]

    def _assert_single(self, name: str) -> None:
        if self.multi_obj:
            raise ValueError(
                f"Multi-objective optimization does not have a single best; "
                f"use get_pf_* instead of {name}"
            )

    # -- pareto front from history -------------------------------------------
    def get_pf_fitness(self, deduplicate: bool = True) -> jax.Array:
        """Approximate pareto-front fitness over all evaluations so far
        (requires ``full_fit_history``)."""
        from ..operators.selection import non_dominate_rank

        if not self.multi_obj:
            raise ValueError("get_pf_fitness is only available for multi-objective optimization.")
        if not self.full_fit_history:
            warnings.warn("`get_pf_fitness` requires enabling `full_fit_history`.")
        # With a vmapped workflow (num_instances set) entries carry a leading
        # instance axis; the pooled front treats every (instance, individual)
        # evaluation as one point.
        all_fit = jnp.concatenate(
            [jnp.asarray(f).reshape(-1, jnp.asarray(f).shape[-1])
             for f in self.fitness_history],
            axis=0,
        )
        if deduplicate:
            all_fit = jnp.unique(all_fit, axis=0)
        # Only the first front is consumed: stop peeling after it.
        rank = non_dominate_rank(all_fit, until_count=1)
        return all_fit[rank == 0] * self.opt_direction

    def get_pf(self, deduplicate: bool = True) -> tuple[jax.Array, jax.Array]:
        """Approximate pareto-front (solutions, fitness) over all evaluations
        (requires both ``full_sol_history`` and ``full_fit_history``)."""
        from ..operators.selection import non_dominate_rank

        if not self.multi_obj:
            raise ValueError("get_pf is only available for multi-objective optimization.")
        if not (self.full_fit_history and self.full_sol_history):
            warnings.warn("`get_pf` requires enabling both `full_sol_history` and `full_fit_history`.")
        all_sol = jnp.concatenate(
            [jnp.asarray(s).reshape(-1, jnp.asarray(s).shape[-1])
             for s in self.solution_history],
            axis=0,
        )
        all_fit = jnp.concatenate(
            [jnp.asarray(f).reshape(-1, jnp.asarray(f).shape[-1])
             for f in self.fitness_history],
            axis=0,
        )
        if deduplicate:
            _, idx = np.unique(np.asarray(all_sol), axis=0, return_index=True)
            idx = jnp.sort(jnp.asarray(idx))
            all_sol, all_fit = all_sol[idx], all_fit[idx]
        rank = non_dominate_rank(all_fit, until_count=1)
        return all_sol[rank == 0], all_fit[rank == 0] * self.opt_direction

    def get_pf_solutions(self, deduplicate: bool = True) -> jax.Array:
        """Solutions of :meth:`get_pf` (requires both full histories)."""
        sol, _ = self.get_pf(deduplicate)
        return sol

    def get_fitness_history(self) -> list:
        """``fitness_history`` with the original optimization sign
        restored (the reference-API accessor form)."""
        return [self.opt_direction * jnp.asarray(f) for f in self.fitness_history]

    def get_solution_history(self) -> list:
        """``solution_history`` as jax arrays (reference-API accessor)."""
        return [jnp.asarray(s) for s in self.solution_history]

    # -- plotting -------------------------------------------------------------
    def plot(self, problem_pf=None, source: str = "eval", **kwargs):
        """Plot the fitness history (1/2/3-objective dispatch), mirroring the
        reference (``eval_monitor.py:338-378``). Requires plotly."""
        if not self.fitness_history and not self.aux_history:
            warnings.warn("No fitness history recorded, return None")
            return None
        from ..vis_tools import plot

        if source == "pop":
            fitness_history = [np.asarray(f) for f in self.aux_history["fit"]]
        elif source == "eval":
            fitness_history = [np.asarray(f) for f in self.get_fitness_history()]
        else:
            raise ValueError(f"Invalid source argument: {source}, expect 'eval' or 'pop'.")
        if not fitness_history:
            warnings.warn(f"No data recorded for source={source!r}, return None")
            return None
        n_objs = 1 if fitness_history[0].ndim == 1 else fitness_history[0].shape[1]
        try:
            if n_objs == 1:
                return plot.plot_obj_space_1d(fitness_history, **kwargs)
            if n_objs == 2:
                return plot.plot_obj_space_2d(fitness_history, problem_pf, **kwargs)
            if n_objs == 3:
                return plot.plot_obj_space_3d(fitness_history, problem_pf, **kwargs)
        except ImportError as e:
            # plotly is optional; degrade gracefully (reference parity:
            # ``eval_monitor.py:345-349``).
            warnings.warn(f"No visualization tool available ({e}), return None")
            return None
        warnings.warn("Not supported yet.")
        return None
